"""vlagent HTTP frontend: every ingest protocol, no storage, no queries.

Reuses the single binary's insert routing (BaseHTTPApp.handle_insert —
reference vlagent serves the same vlinsert protocol surface,
app/vlagent/main.go)."""

from __future__ import annotations

import time

from ..obs import ingestledger
from .app import BaseHTTPApp, Metrics
from .vlselect import HTTPError


class AgentServer(BaseHTTPApp):
    def __init__(self, agent, listen_addr: str = "127.0.0.1",
                 port: int = 0):
        self.agent = agent
        self.sink = agent
        self.metrics = Metrics()
        self.start_time = time.monotonic()
        self._start_http(listen_addr, port)

    def route(self, h, path, args, body, ctype) -> None:
        if path in ("/health", "/-/healthy", "/ping", "/insert/ready"):
            self.respond(h, 200, "text/plain", b"OK")
            return
        if path == "/metrics":
            out = []
            for name in sorted(self.metrics.counters):
                out.append(f"{name} {self.metrics.counters[name]}")
            out.append(f"vlagent_pending_bytes "
                       f"{self.agent.pending_bytes()}")
            out.append(f"vlagent_rows_forwarded_total "
                       f"{self.agent.rows_forwarded}")
            out.append(f"vlagent_bytes_forwarded_total "
                       f"{self.agent.bytes_forwarded}")
            for c in self.agent.clients:
                lbl = f'{{url="{c.url}"}}'
                out.append(f"vlagent_delivered_blocks_total{lbl} "
                           f"{c.delivered_blocks}")
                out.append(f"vlagent_delivery_errors_total{lbl} {c.errors}")
                out.append(f"vlagent_queue_entries{lbl} "
                           f"{c.queue.pending_entries()}")
                out.append(f"vlagent_queue_oldest_age_seconds{lbl} "
                           f"{c.queue.oldest_age_seconds():.3f}")
            for base, labels, v in ingestledger.metrics_samples():
                lbl = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
                out.append(f"{base}{{{lbl}}} {v}" if lbl else f"{base} {v}")
            self.respond(h, 200, "text/plain",
                         ("\n".join(out) + "\n").encode())
            return
        if path == "/insert/status":
            payload = ingestledger.status_payload()
            payload["status"] = "ok"
            payload["queues"] = [
                # vlint: allow-per-row-emit(status payload, bounded by remote count)
                {"url": c.url,
                 "pending_bytes": c.queue.pending_bytes(),
                 "entries": c.queue.pending_entries(),
                 "oldest_age_seconds":
                     round(c.queue.oldest_age_seconds(), 3),
                 "delivered_blocks": c.delivered_blocks,
                 "dropped_blocks": c.dropped_blocks,
                 "errors": c.errors}
                for c in self.agent.clients]
            self.respond_json(h, payload)
            return
        if path == "/":
            self.respond_json(h, {
                "app": "vlagent",
                "uptime_seconds": round(time.monotonic() - self.start_time, 1)})
            return
        if path.startswith("/insert/"):
            self.handle_insert(h, path, args, body, ctype)
            return
        raise HTTPError(404, f"unknown path {path}")
