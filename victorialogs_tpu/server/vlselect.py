"""Query HTTP API handlers: /select/logsql/*.

Reference: app/vlselect (endpoints main.go:212-274, handlers in
app/vlselect/logsql): streamed NDJSON query results, hits histograms via an
injected `stats by (_time:step) count()` pipe (logsql.go:113-170), facets,
field/stream introspection, Prometheus-style stats_query[_range], live tail.
"""

from __future__ import annotations

import json
import math
import time

from ..engine.block_result import format_rfc3339, parse_rfc3339
from ..engine.searcher import (get_field_names, get_field_values, run_query,
                               run_query_collect,
                               run_query_collect_columns)
from ..obs import activity, slowlog, tracing
from ..logsql.duration import parse_duration, ts_bounds
from ..logsql.parser import (MAX_TS, MIN_TS, ParseError, Query, parse_query,
                             parse_filter_string)
from ..logsql.filters import FilterAnd, FilterIn
from ..logsql.pipes import PipeStats, ByField, PipeLimit, PipeOffset
from ..logsql import stats_funcs as sf
from .insertutil import get_tenant_id


class HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _parse_time_arg(v: str, default: int, end: bool = False) -> int:
    if not v:
        return default
    if v == "now":
        return time.time_ns()
    d = parse_duration(v)
    if d is not None:
        return time.time_ns() - abs(d)
    tb = ts_bounds(v)
    if tb is not None:
        return tb[1] if end else tb[0]
    try:  # unix seconds / millis / nanos
        iv = float(v)
        from .insertutil import parse_timestamp
        ts = parse_timestamp(int(iv) if iv.is_integer() else iv)
        if ts is not None:
            return ts
    except ValueError:
        pass
    raise HTTPError(400, f"cannot parse time arg {v!r}")


def parse_common_args(storage, args, headers) -> tuple[Query, list]:
    qs = args.get("query", "")
    if not qs:
        raise HTTPError(400, "missing query arg")
    now = time.time_ns()
    ts = _parse_time_arg(args.get("time", ""), now, end=True)
    try:
        q = parse_query(qs, timestamp=ts)
    except (ParseError, ValueError) as e:
        raise HTTPError(400, f"cannot parse query: {e}")
    start = _parse_time_arg(args.get("start", ""), MIN_TS)
    end = _parse_time_arg(args.get("end", ""), MAX_TS, end=True)
    if start != MIN_TS or end != MAX_TS:
        q.add_time_filter(start, end)
    for extra_arg in ("extra_filters", "extra_stream_filters"):
        ef = args.get(extra_arg, "")
        if ef:
            _apply_extra_filters(q, ef)
    tenant = get_tenant_id(headers, args)
    return q, [tenant]


def _apply_extra_filters(q: Query, ef: str) -> None:
    try:
        obj = json.loads(ef)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        fs = []
        for k, vals in obj.items():
            if isinstance(vals, str):
                vals = [vals]
            fs.append(FilterIn(k, [str(v) for v in vals]))
        extra = FilterAnd(fs) if len(fs) > 1 else fs[0]
    else:
        try:
            extra = parse_filter_string(ef)
        except (ParseError, ValueError) as e:
            raise HTTPError(400, f"cannot parse extra_filters: {e}")
    f = q.filter
    if isinstance(f, FilterAnd):
        f.filters.insert(0, extra)
    else:
        q.filter = FilterAnd([extra, f])


DEFAULT_MAX_QUERY_DURATION_S = 30.0


def query_timeout_s(args) -> float:
    """Seconds of time budget for one request: per-request `timeout`
    arg capped by the -search.maxQueryDuration default.  Shared by the
    execution deadline (query_deadline) and the admission controller's
    deadline-aware shedding (server/app.py)."""
    t = args.get("timeout", "")
    secs = DEFAULT_MAX_QUERY_DURATION_S
    if t:
        d = parse_duration(t)
        if d is not None and d > 0:
            secs = min(d / 1e9, DEFAULT_MAX_QUERY_DURATION_S * 10)
    return secs


def query_deadline(args) -> float:
    """Monotonic deadline for one query: per-request `timeout` arg capped
    by the -search.maxQueryDuration default (reference
    app/vlselect/main.go:133-150, 277-287)."""
    return time.monotonic() + query_timeout_s(args)


def _int_arg(args, name, default=0) -> int:
    v = args.get(name, "")
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        raise HTTPError(400, f"invalid {name} arg {v!r}")


# ---------------- tracing plumbing (?trace=1 / slow-query log) ----------------

def want_trace(args) -> bool:
    return args.get("trace", "") in ("1", "true", "yes")


def _trace_root(args, q: Query):
    """A root span when the request asked for a trace OR the slow-query
    log is armed (a slow query without a trace is exactly what the log
    exists to avoid); None keeps the zero-cost no-op path."""
    if want_trace(args) or slowlog.enabled():
        return tracing.make_root("query", query=q.to_string())
    return None


def _partial_block(act) -> dict | None:
    """The ``"partial"`` payload block when the cluster scatter-gather
    degraded to surviving nodes (cluster.py stamps the record); None on
    a complete answer."""
    failed = act.counter("partial_failed_nodes")
    if failed:
        return {"failed_nodes": list(failed)}
    return None


def _run_collect_traced(storage, tenants, q, args, runner, endpoint,
                        collect=run_query_collect):
    """A collect entry point (run_query_collect or its columnar twin
    run_query_collect_columns) under an optional trace and an
    active-query registry record; returns (result, tree, partial)
    where tree is the span-tree dict only when the request asked for
    it and partial is the ``"partial"`` payload block (or None).
    Emits the slow-query line either way, with the qid correlating it
    to active_queries/traces."""
    root = _trace_root(args, q)
    t0 = time.monotonic()
    # reuse the record the admission layer registered (server/app.py);
    # self-register when called without it (tests, embedded use)
    with activity.reuse_or_track(endpoint, q.to_string(),
                                 tenants[0]) as act:
        if root is not None:
            root.set("qid", act.qid)
        try:
            with tracing.activate(root):
                result = collect(storage, tenants, q, runner=runner,
                                 deadline=query_deadline(args))
            # exec/drain split: the engine walk is done; what remains
            # (JSON shaping, response write) is drain
            act.mark_exec_done()
        finally:
            # in finally: the slowest queries are exactly the ones that
            # die on the deadline — they must still produce their
            # slow-log line
            slowlog.maybe_log(endpoint, q.to_string(),
                              time.monotonic() - t0, root, qid=act.qid)
        partial = _partial_block(act)
    tree = root.to_dict() if root is not None and want_trace(args) \
        else None
    return result, tree, partial


# ---------------- ?explain=1 / ?explain=analyze ----------------

def want_explain(args) -> str:
    """'' (no explain), 'plan' (?explain=1) or 'analyze'
    (?explain=analyze); anything else is a client error."""
    v = args.get("explain", "")
    if not v:
        return ""
    if v in ("1", "true", "yes", "plan"):
        return "plan"
    if v == "analyze":
        return "analyze"
    raise HTTPError(400, f"invalid explain arg {v!r} "
                         f"(use explain=1 or explain=analyze)")


def handle_explain(storage, path, args, headers, runner=None) -> dict:
    """?explain on the query-execution endpoints: the priced physical
    plan tree (obs/explain.py) for EXACTLY the query the endpoint would
    run — including its injected pipes (hits' stats pipe, facets'
    pipe, stats_query_range's _time bucketing).

    explain=1 never executes: zero device dispatches, nothing read past
    part headers / stream indexes / bloom sidecars.  explain=analyze
    executes once and grafts the run's actuals (span-tree per-unit
    timings, activity counters) onto the same tree.  On a cluster
    frontend the per-node trees merge under storage_node nodes exactly
    like ?trace=1."""
    mode = want_explain(args)
    q, tenants = parse_common_args(storage, args, headers)
    if path.endswith("/query"):
        _query_pipes(q, args)
    elif path.endswith("/hits"):
        _hits_pipes(q, args)
    elif path.endswith("/facets"):
        _facets_pipes(q, args)
    elif path.endswith("/stats_query"):
        _require_stats_query(q)
    elif path.endswith("/stats_query_range"):
        _stats_range_pipes(q, args)
    from ..obs import explain as _explain
    if hasattr(storage, "net_explain"):
        # cluster frontend: scatter the explain, merge per-node trees
        # under storage_node nodes (server/cluster.py)
        tree = storage.net_explain(tenants, q, mode,
                                   deadline=query_deadline(args),
                                   include_trace=mode == "analyze"
                                   and want_trace(args))
    else:
        tree = _explain.build_plan(storage, tenants, q, runner=runner)
        if mode == "analyze":
            _explain.analyze(storage, tenants, q, tree, runner=runner,
                             deadline=query_deadline(args),
                             endpoint=path,
                             include_trace=want_trace(args))
    tree["endpoint"] = path
    return {"status": "ok", "explain": tree}


# ---------------- /select/logsql/query ----------------

def handle_query(storage, args, headers, runner=None):
    """Returns an iterator of NDJSON chunks.

    With ?trace=1 the row lines are bit-identical to the untraced
    response; ONE extra final line carries the span tree as
    {"_trace": {...}}."""
    q, tenants = parse_common_args(storage, args, headers)
    _query_pipes(q, args)

    # stream results as blocks arrive; the shared worker protocol
    # (bounded queue + abandon-stream cancellation) lives in streamwork
    from ..engine.emit import ndjson_block
    from .streamwork import stream_blocks

    def encode(br):
        # columnar emit: harvested bitmaps -> response bytes without
        # per-row dicts (engine/emit.py; VL_NATIVE_EMIT=0 kill-switch)
        data = ndjson_block(br)
        return data if data else None

    root = _trace_root(args, q)
    deadline = query_deadline(args)

    def gen():
        # the registry record covers the whole response stream: the
        # admission layer's record is reused (or one registers when the
        # response starts iterating) and deregisters on every exit
        # path (done, deadline, disconnect)
        with activity.reuse_or_track("/select/logsql/query",
                                     q.to_string(), tenants[0]) as act:
            if root is not None:
                root.set("qid", act.qid)

            def run(sink):
                # the query executes on streamwork's worker thread:
                # activate the trace and re-enter the registry record
                # THERE (contextvars don't cross thread spawns); the
                # activation also closes the root on every exit path
                with tracing.activate(root), activity.use_activity(act):
                    run_query(storage, tenants, q, write_block=sink,
                              runner=runner, deadline=deadline)
                    # exec/drain split: the last unit is harvested and
                    # every block is in the response queue; what's left
                    # is the CLIENT draining the stream.  (The bounded
                    # queue means a stalled client can still back-
                    # pressure sink() writes — exec_s includes that,
                    # bounded at 64 chunks, drain_s gets the rest.)
                    activity.current_activity().mark_exec_done()

            t0 = time.monotonic()
            try:
                yield from stream_blocks(run, encode)
            except GeneratorExit:
                # the HTTP peer went away mid-stream: mark the record
                # abandoned and trip the cancel flag so the pipeline
                # drain path stops the device walk instead of finishing
                # a dead query
                act.abandon()
                raise
            finally:
                # in finally: deadline kills (QueryTimeoutError
                # re-raised from the worker) and client disconnects
                # (GeneratorExit at the yield) are exactly the slow
                # queries the log is for
                slowlog.maybe_log("/select/logsql/query", q.to_string(),
                                  time.monotonic() - t0, root,
                                  qid=act.qid)
            partial = _partial_block(act)
            if partial is not None:
                # row lines stay bit-identical to a complete answer;
                # ONE extra final line marks the degradation (the
                # X-VL-Partial header additionally covers every case
                # where the node loss preceded the first output chunk)
                yield json.dumps({"_partial": partial},
                                 ensure_ascii=False,
                                 separators=(",", ":")) + "\n"
            if root is not None and want_trace(args):
                yield json.dumps({"_trace": root.to_dict()},
                                 ensure_ascii=False,
                                 separators=(",", ":")) + "\n"

    return gen()


# ---------------- endpoint pipe preparation ----------------
#
# Each query-execution endpoint rewrites the parsed query's pipe chain
# before running it.  The rewrites live in these helpers so the EXPLAIN
# path (handle_explain) plans EXACTLY the query the endpoint would
# execute — injected stats pipes and all — instead of the raw input.

def _query_pipes(q: Query, args) -> None:
    """/select/logsql/query: offset + limit pushdown."""
    limit = _int_arg(args, "limit", 1000)
    offset = _int_arg(args, "offset", 0)
    if offset:
        q.pipes.append(PipeOffset(offset))
    if limit > 0:
        q.pipes.append(PipeLimit(limit))


def _hits_pipes(q: Query, args) -> list:
    """/select/logsql/hits: the injected `stats by (_time:step [, f..])
    count() hits` pipe; returns the extra group fields."""
    step = args.get("step", "1d")
    if parse_duration(step) is None:
        raise HTTPError(400, f"invalid step {step!r}")
    offset_s = args.get("offset", "0s")
    fields = [f.strip() for f in args.get("field", "").split(",")
              if f.strip()] + \
             [f.strip() for f in args.get("fields", "").split(",")
              if f.strip()]
    by = [ByField("_time", bucket=step, bucket_offset=offset_s)] + \
        [ByField(f) for f in fields]
    fn = sf.StatsCount([])
    fn.out_name = "hits"
    q.pipes.append(PipeStats(by, [fn]))
    return fields


def _facets_pipes(q: Query, args) -> None:
    from ..logsql.pipes_transform import PipeFacets
    q.pipes.append(PipeFacets(
        limit=_int_arg(args, "limit", 10),
        max_values_per_field=_int_arg(args, "max_values_per_field", 1000),
        max_value_len=_int_arg(args, "max_value_len", 1000),
        keep_const_fields=bool(args.get("keep_const_fields", ""))))


def _stats_range_pipes(q: Query, args) -> PipeStats:
    sp = _require_stats_query(q)
    step = args.get("step", "1d")
    if parse_duration(step) is None:
        raise HTTPError(400, f"invalid step {step!r}")
    if not any(b.name == "_time" for b in sp.by):
        sp.by.insert(0, ByField("_time", bucket=step))
    return sp


# ---------------- /select/logsql/hits ----------------

def handle_hits(storage, args, headers, runner=None) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    fields = _hits_pipes(q, args)
    # columnar collect: the stats output arrives as bulk columns (one
    # contract for local and cluster paths) — group rows are zipped
    # from the lists, never materialized as dicts
    (cols, n), trace_tree, partial = _run_collect_traced(
        storage, tenants, q, args, runner, "/select/logsql/hits",
        collect=run_query_collect_columns)
    tcol = cols.get("_time") or [""] * n
    hcol = cols.get("hits") or [""] * n
    fcols = [cols.get(f) or [""] * n for f in fields]
    groups: dict = {}
    for i in range(n):
        key = tuple((f, fc[i]) for f, fc in zip(fields, fcols))
        g = groups.setdefault(key, {"fields": dict(key), "timestamps": [],
                                    "values": [], "total": 0})
        g["timestamps"].append(tcol[i])
        hits = int(hcol[i] or "0")
        g["values"].append(hits)
        g["total"] += hits
    out = {"hits": sorted(groups.values(),
                          key=lambda g: -g["total"])}
    if partial is not None:
        out["partial"] = partial
    if trace_tree is not None:
        out["trace"] = trace_tree
    return out


# ---------------- /select/logsql/facets ----------------

def handle_facets(storage, args, headers, runner=None) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    _facets_pipes(q, args)
    (cols, n), trace_tree, partial = _run_collect_traced(
        storage, tenants, q, args, runner, "/select/logsql/facets",
        collect=run_query_collect_columns)
    out: dict[str, list] = {}
    for fname, fval, hits in zip(cols.get("field_name") or [],
                                 cols.get("field_value") or [],
                                 cols.get("hits") or []):
        # vlint: allow-per-row-emit(facet OUTPUT groups, bounded by limit*fields)
        out.setdefault(fname, []).append(
            {"field_value": fval, "hits": int(hits)})
    # vlint: allow-per-row-emit(facet OUTPUT: one dict per faceted field)
    res = {"facets": [{"field_name": f, "values": v}
                      for f, v in sorted(out.items())]}
    if partial is not None:
        res["partial"] = partial
    if trace_tree is not None:
        res["trace"] = trace_tree
    return res


# ---------------- field/stream introspection ----------------

def handle_field_names(storage, args, headers) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    return {"values": get_field_names(storage, tenants, q)}


def handle_field_values(storage, args, headers) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    field = args.get("field", "")
    if not field:
        raise HTTPError(400, "missing field arg")
    limit = _int_arg(args, "limit", 0)
    return {"values": get_field_values(storage, tenants, q, field, limit)}


def handle_streams(storage, args, headers) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    limit = _int_arg(args, "limit", 0)
    return {"values": get_field_values(storage, tenants, q, "_stream",
                                       limit)}


def handle_stream_ids(storage, args, headers) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    limit = _int_arg(args, "limit", 0)
    return {"values": get_field_values(storage, tenants, q, "_stream_id",
                                       limit)}


def handle_stream_field_names(storage, args, headers) -> dict:
    from ..storage.stream_filter import parse_stream_tags
    q, tenants = parse_common_args(storage, args, headers)
    hits: dict[str, int] = {}

    def sink(br):
        for v in br.column("_stream"):
            for name in parse_stream_tags(v):
                hits[name] = hits.get(name, 0) + 1
    run_query(storage, tenants, q, write_block=sink)
    # vlint: allow-per-row-emit(introspection OUTPUT: one dict per tag name)
    return {"values": [{"value": k, "hits": str(hits[k])}
                       for k in sorted(hits)]}


def handle_stream_field_values(storage, args, headers) -> dict:
    from ..storage.stream_filter import parse_stream_tags
    q, tenants = parse_common_args(storage, args, headers)
    field = args.get("field", "")
    if not field:
        raise HTTPError(400, "missing field arg")
    limit = _int_arg(args, "limit", 0)
    hits: dict[str, int] = {}

    def sink(br):
        for v in br.column("_stream"):
            tags = parse_stream_tags(v)
            if field in tags:
                hits[tags[field]] = hits.get(tags[field], 0) + 1
    run_query(storage, tenants, q, write_block=sink)
    # vlint: allow-per-row-emit(introspection OUTPUT: one dict per tag value)
    out = [{"value": k, "hits": str(v)}
           for k, v in sorted(hits.items(), key=lambda kv: (-kv[1], kv[0]))]
    if limit:
        out = out[:limit]
    return {"values": out}


# ---------------- stats_query / stats_query_range ----------------

def _require_stats_query(q: Query) -> PipeStats:
    for p in reversed(q.pipes):
        if isinstance(p, PipeStats):
            return p
    raise HTTPError(400, "query must end with a `stats` pipe")


def handle_stats_query(storage, args, headers, runner=None) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    sp = _require_stats_query(q)
    ts = _parse_time_arg(args.get("time", ""), time.time_ns(), end=True)
    (cols, nrows), trace_tree, partial = _run_collect_traced(
        storage, tenants, q, args, runner, "/select/logsql/stats_query",
        collect=run_query_collect_columns)
    result = []
    by_names = [b.name for b in sp.by]
    by_cols = [cols.get(n) or [""] * nrows for n in by_names]
    fn_cols = [cols.get(fn.out_name) or [""] * nrows
               for fn in sp.funcs]
    for i in range(nrows):
        for fn, vc in zip(sp.funcs, fn_cols):
            metric = {"__name__": fn.out_name}
            for n, bc in zip(by_names, by_cols):
                if bc[i] != "":
                    metric[n] = bc[i]
            # vlint: allow-per-row-emit(stats OUTPUT groups, bounded by group count)
            result.append({"metric": metric,
                           "value": [ts / 1e9, vc[i]]})
    out = {"status": "success",
           "data": {"resultType": "vector", "result": result}}
    if partial is not None:
        out["partial"] = partial
    if trace_tree is not None:
        out["trace"] = trace_tree
    return out


def handle_stats_query_range(storage, args, headers, runner=None) -> dict:
    q, tenants = parse_common_args(storage, args, headers)
    sp = _stats_range_pipes(q, args)
    (cols, nrows), trace_tree, partial = _run_collect_traced(
        storage, tenants, q, args, runner,
        "/select/logsql/stats_query_range",
        collect=run_query_collect_columns)
    series: dict = {}
    by_names = [b.name for b in sp.by if b.name != "_time"]
    tcol = cols.get("_time") or [""] * nrows
    by_cols = [cols.get(n) or [""] * nrows for n in by_names]
    fn_cols = [cols.get(fn.out_name) or [""] * nrows
               for fn in sp.funcs]
    for i in range(nrows):
        t = parse_rfc3339(tcol[i]) or 0
        for fn, vc in zip(sp.funcs, fn_cols):
            key = (fn.out_name,) + tuple((n, bc[i])
                                         for n, bc in zip(by_names,
                                                          by_cols))
            s = series.setdefault(key, {"metric": dict(
                [("__name__", fn.out_name)] +
                [(n, bc[i]) for n, bc in zip(by_names, by_cols)
                 if bc[i] != ""]),
                "values": []})
            s["values"].append([t / 1e9, vc[i]])
    for s in series.values():
        s["values"].sort()
    out = {"status": "success",
           "data": {"resultType": "matrix",
                    "result": list(series.values())}}
    if partial is not None:
        out["partial"] = partial
    if trace_tree is not None:
        out["trace"] = trace_tree
    return out


# ---------------- live tail ----------------

def handle_tail(storage, args, headers, stop_check=None, runner=None):
    """Generator yielding NDJSON chunks for new rows (poll loop, ~1s period
    with a lag offset — reference logsql.go:497-580)."""
    q, tenants = parse_common_args(storage, args, headers)
    if not q.can_live_tail():
        raise HTTPError(400, "query contains pipes that cannot live-tail")
    lag_ns = 2_500_000_000
    last_ts = time.time_ns() - lag_ns
    # one registry record for the whole tail connection: cancel_query
    # on its qid (or a client disconnect) ends the tail; the inner
    # polls inherit the record ambiently, so a cancel also drains a
    # poll that is mid-scan
    with activity.reuse_or_track("/select/logsql/tail", q.to_string(),
                                 tenants[0]) as act:
        try:
            yield from _tail_loop(storage, tenants, q, act, lag_ns,
                                  last_ts, stop_check, runner)
        except GeneratorExit:
            act.abandon()
            raise


def _tail_loop(storage, tenants, q, act, lag_ns, last_ts, stop_check,
               runner):
    from ..engine.emit import ndjson_block
    while True:
        if stop_check is not None and stop_check():
            return
        if act.is_cancelled():
            return
        now_end = time.time_ns() - lag_ns
        qq = q.clone()
        qq.add_time_filter(last_ts + 1, now_end)
        # columnar emit per block; the cross-block _time sort happens on
        # (int64-ns, line-bytes) pairs, never on row dicts.  Typed keys
        # also FIX the old lexical sort: trimmed RFC3339Nano misorders
        # sub-second rows ("..00.5Z" < "..00Z" byte-wise); blocks come
        # with their timestamps attached, so ns order is free.  Rows
        # whose _time is projected out keep arrival order (key 0),
        # like the old "" keys did.
        pairs: list = []

        def sink(br):
            if br.nrows == 0:
                return
            lines = ndjson_block(br).split(b"\n")[:br.nrows]
            names = br.column_names()
            native_keys = br.native_time_keys()
            if "_time" not in names:
                # projected out: arrival order, like the old "" keys
                keys = [0] * br.nrows
            elif native_keys is not None:
                # storage-backed or cluster wire view: the displayed
                # _time IS the native int64 array — sort on it directly
                keys = native_keys.tolist()
            else:
                # a pipe may have rewritten _time (copy/rename/extract):
                # the sort key must follow the DISPLAYED value, not the
                # original ingestion timestamps the block still carries
                keys = [parse_rfc3339(v) or 0
                        for v in br.column("_time")]
            pairs.extend(zip(keys, lines))
        run_query(storage, tenants, qq, write_block=sink, runner=runner)
        pairs.sort(key=lambda kv: kv[0])
        if pairs:
            yield b"\n".join(ln for _k, ln in pairs) + b"\n"
        else:
            yield ""  # keep-alive chunk
        last_ts = now_end
        # sleep on the cancel flag so cancel_query wakes the tail
        # immediately instead of after the poll period
        if act.wait_cancelled(1.0):
            return
