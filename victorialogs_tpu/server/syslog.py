"""Syslog ingestion: RFC3164 / RFC5424 parsing + TCP/UDP listeners.

Reference: app/vlinsert/syslog (listeners with TLS/timezone/year-inference
flags — syslog.go:94-160) and lib/logstorage/syslog_parser.go for field
extraction: priority/facility/severity, timestamp, hostname, app_name,
proc_id, msg_id, structured data, message.
"""

from __future__ import annotations

import datetime
import re
import socket
import socketserver
import threading
import time

from ..engine.block_result import parse_rfc3339
from .insertutil import CommonParams, LogMessageProcessor

_RFC3164_RE = re.compile(
    r"^(?P<mon>[A-Z][a-z]{2}) +(?P<day>\d{1,2}) "
    r"(?P<time>\d{2}:\d{2}:\d{2}) (?P<host>\S+) (?P<rest>.*)$", re.DOTALL)
_TAG_RE = re.compile(r"^(?P<tag>[^\s:\[\]]+)(?:\[(?P<pid>\d+)\])?: ?")

_MONTHS = {m: i + 1 for i, m in enumerate(
    ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug", "Sep", "Oct",
     "Nov", "Dec"])}

_SEVERITIES = ["emerg", "alert", "crit", "err", "warning", "notice", "info",
               "debug"]


def parse_syslog_message(line: str, current_year: int | None = None,
                         tz_offset_ns: int = 0) -> list[tuple[str, str]]:
    """Parse one syslog line into log fields (format auto-detected)."""
    fields: list[tuple[str, str]] = []
    pri = None
    if line.startswith("<"):
        end = line.find(">")
        if 0 < end <= 4 and line[1:end].isdigit():
            pri = int(line[1:end])
            line = line[end + 1:]
    if pri is not None:
        fields.append(("priority", str(pri)))
        fields.append(("facility", str(pri // 8)))
        sev = pri % 8
        fields.append(("severity", str(sev)))
        fields.append(("level", _SEVERITIES[sev]))

    if line.startswith("1 "):
        fields.extend(_parse_rfc5424(line[2:]))
        fields.append(("format", "rfc5424"))
        return fields

    m = _RFC3164_RE.match(line)
    if m is not None:
        mon = _MONTHS.get(m.group("mon"))
        if mon is not None:
            year = current_year or time.gmtime().tm_year
            hh, mm, ss = m.group("time").split(":")
            try:
                dt = datetime.datetime(year, mon, int(m.group("day")),
                                       int(hh), int(mm), int(ss),
                                       tzinfo=datetime.timezone.utc)
                ts = int(dt.timestamp()) * 1_000_000_000 - tz_offset_ns
                # year inference: timestamps far in the future belong to
                # the previous year (reference year-inference logic)
                if ts > time.time_ns() + 2 * 86400 * 1_000_000_000:
                    dt = dt.replace(year=year - 1)
                    ts = int(dt.timestamp()) * 1_000_000_000 - tz_offset_ns
                fields.append(("timestamp",
                               dt.strftime("%Y-%m-%dT%H:%M:%SZ")))
            except ValueError:
                pass
            fields.append(("hostname", m.group("host")))
            rest = m.group("rest")
            tm = _TAG_RE.match(rest)
            if tm is not None:
                fields.append(("app_name", tm.group("tag")))
                if tm.group("pid"):
                    fields.append(("proc_id", tm.group("pid")))
                rest = rest[tm.end():]
            fields.append(("_msg", rest))
            fields.append(("format", "rfc3164"))
            return fields

    fields.append(("_msg", line))
    fields.append(("format", "unknown"))
    return fields


def _parse_rfc5424(rest: str) -> list[tuple[str, str]]:
    fields: list[tuple[str, str]] = []
    parts = rest.split(" ", 5)
    if len(parts) < 6:
        parts += ["-"] * (6 - len(parts))
    ts_s, host, app, procid, msgid, tail = parts
    if ts_s != "-":
        fields.append(("timestamp", ts_s))
    if host != "-":
        fields.append(("hostname", host))
    if app != "-":
        fields.append(("app_name", app))
    if procid != "-":
        fields.append(("proc_id", procid))
    if msgid != "-":
        fields.append(("msg_id", msgid))
    # structured data
    tail = tail.lstrip()
    if tail.startswith("["):
        i = 0
        while i < len(tail) and tail[i] == "[":
            end = _sd_end(tail, i)
            if end < 0:
                break
            sd = tail[i + 1:end]
            fields.extend(_parse_sd_element(sd))
            i = end + 1
            while i < len(tail) and tail[i] == " ":
                i += 1
                break
        tail = tail[i:].lstrip()
    elif tail.startswith("- "):
        tail = tail[2:]
    elif tail == "-":
        tail = ""
    fields.append(("_msg", tail))
    return fields


def _sd_end(s: str, start: int) -> int:
    i = start + 1
    in_quote = False
    while i < len(s):
        c = s[i]
        if c == "\\" and in_quote:
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
        elif c == "]" and not in_quote:
            return i
        i += 1
    return -1


def _parse_sd_element(sd: str) -> list[tuple[str, str]]:
    out = []
    parts = sd.split(" ", 1)
    sd_id = parts[0]
    rest = parts[1] if len(parts) > 1 else ""
    for m in re.finditer(r'(\S+?)="((?:[^"\\]|\\.)*)"', rest):
        out.append((f"{sd_id}.{m.group(1)}",
                    m.group(2).replace('\\"', '"').replace("\\\\", "\\")))
    return out


def _ts_of(fields: list[tuple[str, str]]):
    for k, v in fields:
        if k == "timestamp":
            return parse_rfc3339(v)
    return None


class SyslogServer:
    """TCP + UDP syslog listeners feeding a LogMessageProcessor."""

    def __init__(self, sink, tenant=None, listen_addr: str = "127.0.0.1",
                 tcp_port: int = 0, udp_port: int = 0,
                 tls_cert_file: str = "", tls_key_file: str = ""):
        from ..storage.log_rows import TenantID
        cp = CommonParams(tenant=tenant or TenantID(),
                          stream_fields=["hostname", "app_name"])
        # columnar: flushed syslog batches build LogColumns and ride the
        # same rows_to_columns -> must_add_columns block-build path as
        # jsonline ingest (parity-tested against the row path)
        self.lmp = LogMessageProcessor(cp, sink, periodic_flush=True,
                                       columnar=True)
        self.tcp_port = self.udp_port = 0
        self._tcp = self._udp = None
        outer = self

        ssl_ctx = None
        if tls_cert_file and tls_key_file:
            # TLS syslog (reference -syslog.tls* flags —
            # app/vlinsert/syslog/syslog.go:94-160)
            import ssl
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(tls_cert_file, tls_key_file)

        if tcp_port >= 0:
            class Handler(socketserver.StreamRequestHandler):
                def handle(self):
                    for raw in self.rfile:
                        line = raw.decode("utf-8", "replace").rstrip("\r\n")
                        if line:
                            outer.ingest_line(line)

            class TCPServer(socketserver.ThreadingTCPServer):
                def get_request(self):
                    sock, addr = super().get_request()
                    if ssl_ctx is not None:
                        sock = ssl_ctx.wrap_socket(sock, server_side=True)
                    return sock, addr
            self._tcp = TCPServer((listen_addr, tcp_port), Handler,
                                  bind_and_activate=True)
            self._tcp.daemon_threads = True
            self.tcp_port = self._tcp.server_address[1]
            threading.Thread(target=self._tcp.serve_forever,
                             daemon=True).start()

        if udp_port >= 0:
            class UHandler(socketserver.DatagramRequestHandler):
                def handle(self):
                    data = self.rfile.read()
                    for raw in data.split(b"\n"):
                        line = raw.decode("utf-8", "replace").strip()
                        if line:
                            outer.ingest_line(line)
            self._udp = socketserver.ThreadingUDPServer(
                (listen_addr, udp_port), UHandler)
            self._udp.daemon_threads = True
            self.udp_port = self._udp.server_address[1]
            threading.Thread(target=self._udp.serve_forever,
                             daemon=True).start()

    def ingest_line(self, line: str) -> None:
        fields = parse_syslog_message(line)
        self.lmp.add_row(_ts_of(fields), fields)

    def flush(self) -> None:
        self.lmp.flush()

    def close(self) -> None:
        if self._tcp:
            self._tcp.shutdown()
            self._tcp.server_close()
        if self._udp:
            self._udp.shutdown()
            self._udp.server_close()
        self.lmp.stop()
