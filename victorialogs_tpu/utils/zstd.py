"""Thread-safe zstd helpers.

zstandard (de)compressor objects are NOT safe for concurrent use from
multiple threads, and this codebase (de)compresses from many: query
workers, the flusher, merge workers, partition-parallel scans, HTTP
handler and cluster fetch threads.  Every caller goes through these
helpers, which keep one context per (thread, level) — no per-call
allocation, no sharing.  (Observed failure mode with a shared object:
sporadic "Data corruption detected" under concurrent flush+query load.)
"""

from __future__ import annotations

import threading

import zstandard

_tls = threading.local()


def compress(data: bytes, level: int = 1) -> bytes:
    key = f"zc{level}"
    zc = getattr(_tls, key, None)
    if zc is None:
        zc = zstandard.ZstdCompressor(level=level)
        setattr(_tls, key, zc)
    return zc.compress(data)


def decompress(data: bytes, max_output_size: int = 0) -> bytes:
    zd = getattr(_tls, "zd", None)
    if zd is None:
        zd = _tls.zd = zstandard.ZstdDecompressor()
    return zd.decompress(data, max_output_size=max_output_size)
