"""Thread-safe zstd helpers.

zstandard (de)compressor objects are NOT safe for concurrent use from
multiple threads, and this codebase (de)compresses from many: query
workers, the flusher, merge workers, partition-parallel scans, HTTP
handler and cluster fetch threads.  Every caller goes through these
helpers, which keep one context per (thread, level) — no per-call
allocation, no sharing.  (Observed failure mode with a shared object:
sporadic "Data corruption detected" under concurrent flush+query load.)
"""

from __future__ import annotations

import threading
import zlib

try:
    import zstandard
except ImportError:
    # containers without the zstandard wheel fall back to zlib below
    zstandard = None

_tls = threading.local()

# zlib-fallback frame marker.  Real zstd frames start with the magic
# 28 B5 2F FD, so the two container formats can never be confused; data
# written by the fallback stays readable if zstandard appears later.
_ZLIB_MAGIC = b"VLZ1"


def compress(data: bytes, level: int = 1) -> bytes:
    if zstandard is None:
        return _ZLIB_MAGIC + zlib.compress(data, min(level, 9))
    key = f"zc{level}"
    zc = getattr(_tls, key, None)
    if zc is None:
        zc = zstandard.ZstdCompressor(level=level)
        setattr(_tls, key, zc)
    return zc.compress(data)


def decompress(data: bytes, max_output_size: int = 0) -> bytes:
    if data[:4] == _ZLIB_MAGIC:
        if max_output_size:
            # enforce the bound DURING decompression (like the zstd
            # path) so a hostile frame can't balloon before the check
            d = zlib.decompressobj()
            out = d.decompress(data[4:], max_output_size)
            if d.unconsumed_tail:
                raise ValueError(
                    f"decompressed size exceeds limit {max_output_size}")
            return out
        return zlib.decompress(data[4:])
    if zstandard is None:
        raise RuntimeError(
            "zstd frame but the zstandard module is unavailable")
    zd = getattr(_tls, "zd", None)
    if zd is None:
        zd = _tls.zd = zstandard.ZstdDecompressor()
    return zd.decompress(data, max_output_size=max_output_size)
