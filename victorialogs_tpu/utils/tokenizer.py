"""Word tokenizer for full-text indexing.

Semantics follow the reference tokenizer (lib/logstorage/tokenizer.go:34-148):
a token is a maximal run of word characters, where word characters are ASCII
letters, digits and '_' (fast path), plus any unicode letter/digit (slow path).
Tokens are what bloom filters index and what `word`/`phrase` filters match on
word boundaries.

The arena tokenizer here is vectorized with numpy over a whole column block at
once (value boundaries force token boundaries), instead of the reference's
per-value byte loop — the same boundary semantics, a layout that also matches
what the TPU staging path needs.
"""

from __future__ import annotations

import re

import numpy as np

# ASCII word-char lookup table: A-Z a-z 0-9 _
_WORD_CHAR = np.zeros(256, dtype=bool)
for _c in range(ord("A"), ord("Z") + 1):
    _WORD_CHAR[_c] = True
for _c in range(ord("a"), ord("z") + 1):
    _WORD_CHAR[_c] = True
for _c in range(ord("0"), ord("9") + 1):
    _WORD_CHAR[_c] = True
_WORD_CHAR[ord("_")] = True
# Non-ASCII bytes participate in (possibly multi-byte) unicode tokens; treating
# every >=0x80 byte as a word char makes UTF-8 letter runs come out as single
# tokens, matching the reference's unicode slow path for letters/digits.
_WORD_CHAR[128:] = True

_TOKEN_RE = re.compile("[A-Za-z0-9_" + chr(0x80) + "-" + chr(0x10FFFF) + "]+")


def is_word_char_table() -> np.ndarray:
    return _WORD_CHAR


def tokenize_string(s: str) -> list[str]:
    """Tokenize a single query-side string into word tokens."""
    if s.isascii():
        return re.findall(r"[A-Za-z0-9_]+", s)
    return _TOKEN_RE.findall(s)


def tokenize_arena(
    arena: np.ndarray, offsets: np.ndarray, lengths: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Tokenize a packed string column.

    arena: uint8[N] concatenated value bytes;
    offsets/lengths: int64[R] per-value spans into the arena.

    Returns (tok_start, tok_end, tok_row): parallel int64 arrays, one entry per
    token, where arena[tok_start:tok_end] is the token and tok_row is the row
    it came from.
    """
    n = arena.shape[0]
    if n == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, z
    mask = _WORD_CHAR[arena]
    # previous-byte mask, with a forced boundary at every value start
    prev = np.empty(n, dtype=bool)
    prev[0] = False
    prev[1:] = mask[:-1]
    starts_at_value = offsets[lengths > 0]
    prev[starts_at_value] = False
    # next-byte mask, with a forced boundary at every value end
    nxt = np.empty(n, dtype=bool)
    nxt[-1] = False
    nxt[:-1] = mask[1:]
    ends = offsets + lengths
    ends_inside = ends[(lengths > 0) & (ends < n)]
    # ends_inside points at the byte *after* a value; the last byte of the
    # value is ends_inside-1 and must not join with the next value's first byte
    nxt[ends_inside - 1] = False

    tok_start = np.nonzero(mask & ~prev)[0]
    tok_end = np.nonzero(mask & ~nxt)[0] + 1
    # map token starts to rows
    tok_row = np.searchsorted(offsets, tok_start, side="right") - 1
    return tok_start, tok_end, tok_row


def unique_tokens_bytes(
    arena: np.ndarray, tok_start: np.ndarray, tok_end: np.ndarray
) -> list[bytes]:
    """Materialize the set of distinct token byte-strings in arena order."""
    seen: set[bytes] = set()
    out: list[bytes] = []
    buf = arena.tobytes()
    for s, e in zip(tok_start.tolist(), tok_end.tolist()):
        t = buf[s:e]
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out
