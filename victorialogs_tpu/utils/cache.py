"""Two-generation rotating cache (reference lib/logstorage/cache.go:13-58).

Entries live in the current generation; hits in the previous generation
promote the entry forward.  Rotation every ~3 minutes (jittered) bounds
both staleness and memory without tracking per-entry ages.  Thread-safe.
"""

from __future__ import annotations

import random
import threading
import time

ROTATE_SECONDS = 3 * 60


class TwoGenCache:
    def __init__(self, rotate_seconds: float = ROTATE_SECONDS):
        self._lock = threading.Lock()
        self._curr: dict = {}
        self._prev: dict = {}
        self._rotate_every = rotate_seconds
        self._next_rotate = time.monotonic() + \
            rotate_seconds * (0.9 + 0.2 * random.random())
        self.hits = 0
        self.misses = 0

    def _maybe_rotate_locked(self) -> None:
        now = time.monotonic()
        if now >= self._next_rotate:
            if now - self._next_rotate >= self._rotate_every:
                # idle past a full extra period: everything is stale
                self._prev = {}
                self._curr = {}
            else:
                self._prev = self._curr
                self._curr = {}
            self._next_rotate = now + self._rotate_every * \
                (0.9 + 0.2 * random.random())

    def get(self, key):
        with self._lock:
            self._maybe_rotate_locked()
            v = self._curr.get(key)
            if v is not None:
                self.hits += 1
                return v
            v = self._prev.get(key)
            if v is not None:
                # promote-on-hit from the previous generation
                self._curr[key] = v
                self.hits += 1
                return v
            self.misses += 1
            return None

    def put(self, key, value) -> None:
        with self._lock:
            self._maybe_rotate_locked()
            self._curr[key] = value

    def clear(self) -> None:
        with self._lock:
            self._curr = {}
            self._prev = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._curr) + len(self._prev)
