"""Disk-backed FIFO queue of byte blocks (the vlagent delivery buffer).

Redesign of the reference's lib/persistentqueue FastQueue
(app/vlagent/remotewrite/remotewrite.go:188-214): writers append
length-prefixed records to rolling segment files; the reader's position is
persisted on ack, so undelivered data survives restarts.  A crash between
write and ack re-delivers (at-least-once), matching the reference.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from collections import deque


SEGMENT_MAX_BYTES = 64 << 20
READER_STATE = "reader.json"


class QueueOverflowError(IOError):
    """append() would exceed max_pending_bytes: the backlog bound hit.
    An IOError for backward compatibility; callers that must react to
    overflow specifically (the cluster ingest spool's counted-and-
    journaled drop path) catch this type."""


class PersistentQueue:
    def __init__(self, path: str, max_pending_bytes: int = 1 << 30):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_pending_bytes = max_pending_bytes
        self._lock = threading.Lock()
        self._data_ready = threading.Condition(self._lock)
        # reader state
        rs_path = os.path.join(path, READER_STATE)
        self._read_seg = 0
        self._read_off = 0
        if os.path.exists(rs_path):
            try:
                with open(rs_path) as f:
                    st = json.load(f)
                self._read_seg = int(st["seg"])
                self._read_off = int(st["off"])
            except (ValueError, KeyError, OSError):
                pass
        # discover existing segments
        segs = sorted(int(n.split("_")[1].split(".")[0])
                      for n in os.listdir(path)
                      if n.startswith("seg_") and n.endswith(".bin"))
        self._write_seg = segs[-1] if segs else self._read_seg
        if self._write_seg < self._read_seg:
            self._write_seg = self._read_seg
        # crash recovery: truncate a torn record at the tail of the write
        # segment, or appended records would be permanently misframed
        self._truncate_torn_tail(self._seg_path(self._write_seg))
        self._writer = open(self._seg_path(self._write_seg), "ab")
        # drop fully-consumed older segments
        for s in segs:
            if s < self._read_seg:
                try:
                    os.unlink(self._seg_path(s))
                except OSError:
                    pass
        # pending bytes are tracked incrementally from here on (one stat
        # sweep at open, then +rec on append / -rec on ack) — stat-ing
        # every live segment per append made ingest cost grow with backlog
        self._pending = self._scan_pending_bytes()
        # record-level visibility for the spool/queue gauges: (bytes
        # left, enqueue mono-time) per record, consumed FIFO on ack.
        # A pre-existing backlog can't be re-framed per record cheaply,
        # so it seeds ONE entry aged by the oldest segment's mtime —
        # entries is then a floor and the age a conservative bound
        self._entries: deque = deque()
        if self._pending:
            try:
                mtime = os.path.getmtime(self._seg_path(self._read_seg))
                # vlint: allow-wall-clock(segment mtime is wall time; converted to the mono clock once at open)
                age = max(0.0, time.time() - mtime)
            except OSError:
                age = 0.0
            self._entries.append([self._pending,
                                  time.monotonic() - age])

    @staticmethod
    def _truncate_torn_tail(path: str) -> None:
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        good = 0
        with open(path, "rb") as f:
            while good + 4 <= size:
                f.seek(good)
                n = struct.unpack(">I", f.read(4))[0]
                if good + 4 + n > size:
                    break  # torn payload
                good += 4 + n
        if good != size:
            with open(path, "r+b") as f:
                f.truncate(good)

    def _seg_path(self, n: int) -> str:
        return os.path.join(self.path, f"seg_{n:08d}.bin")

    # ---- writer ----
    # vlint: allow-lock-blocking-call(durable queue: fsync under lock)
    def append(self, data: bytes) -> None:
        """Durably append one block (fsynced before returning)."""
        rec = struct.pack(">I", len(data)) + data
        with self._lock:
            if self._pending + len(rec) > self.max_pending_bytes:
                raise QueueOverflowError("persistent queue overflow")
            if self._writer.tell() >= SEGMENT_MAX_BYTES:
                self._writer.flush()
                os.fsync(self._writer.fileno())
                self._writer.close()
                self._write_seg += 1
                self._writer = open(self._seg_path(self._write_seg), "ab")
            self._writer.write(rec)
            self._writer.flush()
            os.fsync(self._writer.fileno())
            self._pending += len(rec)
            self._entries.append([len(rec), time.monotonic()])
            self._data_ready.notify_all()

    def _scan_pending_bytes(self) -> int:
        total = 0
        for s in range(self._read_seg, self._write_seg + 1):
            try:
                sz = os.path.getsize(self._seg_path(s))
            except OSError:
                continue
            total += sz - (self._read_off if s == self._read_seg else 0)
        return total

    def pending_bytes(self) -> int:
        with self._lock:
            return self._pending

    def pending_entries(self) -> int:
        """Undelivered records (a pre-existing backlog counts as one)."""
        with self._lock:
            return len(self._entries)

    def oldest_age_seconds(self) -> float:
        """Age of the oldest undelivered record; 0.0 when drained —
        the wedged-spool signal the chaos dashboards watch."""
        with self._lock:
            if not self._entries:
                return 0.0
            return max(0.0, time.monotonic() - self._entries[0][1])

    # ---- reader ----
    def read(self, timeout: float | None = None) -> bytes | None:
        """Peek the next block (does NOT advance); None on timeout.

        Call ack() after successful delivery to advance durably."""
        with self._lock:
            while True:
                rec = self._read_locked()
                if rec is not None:
                    return rec
                if timeout is not None:
                    if not self._data_ready.wait(timeout):
                        return None
                    continue
                return None

    # vlint: allow-lock-blocking-call(segment read under lock by design)
    def _read_locked(self) -> bytes | None:
        while True:
            seg_path = self._seg_path(self._read_seg)
            try:
                size = os.path.getsize(seg_path)
            except OSError:
                size = 0
            if self._read_off + 4 <= size:
                with open(seg_path, "rb") as f:
                    f.seek(self._read_off)
                    hdr = f.read(4)
                    n = struct.unpack(">I", hdr)[0]
                    data = f.read(n)
                if len(data) < n:
                    return None  # torn tail: wait for the writer
                return data
            if self._read_seg < self._write_seg:
                # segment exhausted: move on, clean up
                try:
                    os.unlink(seg_path)
                except OSError:
                    pass
                self._read_seg += 1
                self._read_off = 0
                continue
            return None

    # vlint: allow-lock-blocking-call(durable reader-state swap)
    def ack(self, data_len: int) -> None:
        """Advance past the block returned by read() (durable)."""
        with self._lock:
            self._read_off += 4 + data_len
            self._pending = max(0, self._pending - (4 + data_len))
            n = 4 + data_len
            while n > 0 and self._entries:
                head = self._entries[0]
                take = min(head[0], n)
                head[0] -= take
                n -= take
                if head[0] == 0:
                    self._entries.popleft()
            tmp = os.path.join(self.path, READER_STATE + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"seg": self._read_seg, "off": self._read_off}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.path, READER_STATE))

    # vlint: allow-lock-blocking-call(shutdown flush under lock)
    def close(self) -> None:
        with self._lock:
            self._writer.flush()
            os.fsync(self._writer.fileno())
            self._writer.close()
