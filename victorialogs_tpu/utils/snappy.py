"""Pure-Python snappy block-format decompressor.

Needed for Loki protobuf push payloads (snappy-framed by Promtail/Grafana
Agent as raw block format).  Decode-only; compression is not needed server
side.
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def decompress(data: bytes) -> bytes:
    i = 0
    n = len(data)
    # uncompressed length varint
    ulen = 0
    shift = 0
    while True:
        if i >= n:
            raise SnappyError("truncated length")
        b = data[i]
        i += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < n:
        tag = data[i]
        i += 1
        elem_type = tag & 3
        if elem_type == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if i + extra > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[i:i + extra], "little")
                i += extra
            ln += 1
            if i + ln > n:
                raise SnappyError("truncated literal")
            out += data[i:i + ln]
            i += ln
            continue
        if elem_type == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8) | data[i]
            i += 1
        elif elem_type == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 2], "little")
            i += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[i:i + 4], "little")
            i += 4
        if off == 0 or off > len(out):
            raise SnappyError("bad copy offset")
        for _ in range(ln):  # overlapping copies must go byte by byte
            out.append(out[-off])
    if len(out) != ulen:
        raise SnappyError(f"length mismatch: {len(out)} != {ulen}")
    return bytes(out)
