"""Memory budget for stateful query pipes.

The reference fails memory-hungry pipes (sort/stats/uniq/top) once their
state passes a fraction of `memory.Allowed()` (pipe_sort.go:144,
pipe_stats.go:314-348) instead of OOMing the process.  allowed() here reads
total RAM once and takes 60% of it, overridable with
VL_MEMORY_ALLOWED_BYTES for tests."""

from __future__ import annotations

from .. import config

_cached: int | None = None


def allowed() -> int:
    global _cached
    env = config.env("VL_MEMORY_ALLOWED_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    if _cached is None:
        total = 1 << 32
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1]) * 1024
                        break
        except OSError:
            pass
        _cached = int(total * 0.6)
    return _cached


class MemoryBudget:
    """Tracks approximate state bytes for one pipe processor."""

    def __init__(self, fraction: float, what: str):
        self.limit = int(allowed() * fraction)
        self.used = 0
        self.what = what

    def add(self, nbytes: int) -> None:
        self.used += nbytes
        if self.used > self.limit:
            raise QueryMemoryError(
                f"memory limit exceeded for {self.what}: state needs more "
                f"than {self.limit} bytes; reduce the query's row/group "
                f"count (e.g. add filters or limits)")


class QueryMemoryError(Exception):
    """Raised when a stateful pipe exceeds its memory budget."""
