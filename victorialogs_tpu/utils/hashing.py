"""Hashing primitives.

The reference hashes tokens with xxhash64 and derives bloom probe positions by
iterating the hash (reference: lib/logstorage/bloomfilter.go:126-170).  We keep
the same *shape* of the scheme — one 64-bit base hash per token, probe
positions derived by a cheap iterated mixer — but define our own iteration
(splitmix64) so the device never needs string hashing: probe positions are pure
integer math on the base hash, computable both on host (numpy) and on device
(jnp, as two uint32 lanes).

Stream IDs are 128-bit hashes of the canonical stream-label string
(reference: lib/logstorage/stream_id.go:11-22, hash128.go).
"""

from __future__ import annotations

import numpy as np

try:  # C-accelerated scalar hashing
    import xxhash as _xxhash

    def xxh64(data: bytes, seed: int = 0) -> int:
        return _xxhash.xxh64_intdigest(data, seed)

    def xxh128(data: bytes, seed: int = 0) -> int:
        return _xxhash.xxh128_intdigest(data, seed)

except ImportError:  # pragma: no cover - xxhash is baked into the image
    raise

_U64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """One splitmix64 round; used to derive bloom probe index streams."""
    x = (x + 0x9E3779B97F4A7C15) & _U64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _U64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _U64
    return z ^ (z >> 31)


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64 numpy array."""
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    z = x
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def hash_tokens(tokens: list[bytes] | list[str]) -> np.ndarray:
    """xxhash64 each token; returns uint64 array."""
    out = np.empty(len(tokens), dtype=np.uint64)
    h = _xxhash.xxh64_intdigest
    for i, t in enumerate(tokens):
        if isinstance(t, str):
            t = t.encode("utf-8")
        out[i] = h(t)
    return out


def cached_token_hashes(owner, tokens) -> np.ndarray:
    """hash_tokens memoized on the owning filter object.

    The same filter leaf probes the same tokens against every part of
    every partition a query touches; hashing them once per query (not
    once per part) keeps the kill-path cost independent of part count.
    Keyed on the token tuple so filters whose values mutate between
    runs (in()/contains_all set_values) never serve stale hashes.
    """
    key = tuple(tokens)
    got = getattr(owner, "_token_hash_cache", None)
    if got is not None and got[0] == key:
        return got[1]
    h = hash_tokens(key)
    try:
        owner._token_hash_cache = (key, h)
    except AttributeError:  # slotted/foreign owner: just skip the memo
        pass
    return h


def stream_id_hash(canonical_tags: bytes) -> tuple[int, int]:
    """128-bit stream hash -> (hi, lo) uint64 pair."""
    h = _xxhash.xxh128_intdigest(canonical_tags)
    return (h >> 64) & _U64, h & _U64
