"""Minimal protobuf wire-format reader (decode only).

Enough to parse OTLP logs and Loki push payloads without a generated-code
dependency (the reference similarly hand-rolls its Loki decoder —
app/vlinsert/loki/pb.go).
"""

from __future__ import annotations

import struct


class PBError(ValueError):
    pass


def read_varint(buf: bytes, i: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if i >= len(buf):
            raise PBError("truncated varint")
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 70:
            raise PBError("varint too long")


def iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's fields.

    wire types: 0 varint (value int), 1 fixed64 (bytes), 2 length-delimited
    (bytes), 5 fixed32 (bytes).
    """
    i = 0
    n = len(buf)
    while i < n:
        key, i = read_varint(buf, i)
        fnum = key >> 3
        wt = key & 7
        if wt == 0:
            v, i = read_varint(buf, i)
            yield fnum, wt, v
        elif wt == 1:
            if i + 8 > n:
                raise PBError("truncated fixed64")
            yield fnum, wt, buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = read_varint(buf, i)
            if i + ln > n:
                raise PBError("truncated bytes field")
            yield fnum, wt, buf[i:i + ln]
            i += ln
        elif wt == 5:
            if i + 4 > n:
                raise PBError("truncated fixed32")
            yield fnum, wt, buf[i:i + 4]
            i += 4
        else:
            raise PBError(f"unsupported wire type {wt}")


def fixed64_u(b: bytes) -> int:
    return struct.unpack("<Q", b)[0]


def fixed64_f(b: bytes) -> float:
    return struct.unpack("<d", b)[0]


def zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)
