"""Per-block scan state: lazy column access + result bitmap.

The CPU analogue of the reference's blockSearch (lib/logstorage/
block_search.go:207-226): wraps one (part, block) pair, caches lazily-read
timestamps / columns / blooms, and lets the filter tree AND itself into a
numpy bool bitmap.  This object is also the staging source for the TPU
runner — device tensors are built from the same cached columns.
"""

from __future__ import annotations

import numpy as np

from ..storage.values_encoder import (EncodedColumn, VT_CONST, VT_DICT,
                                      VT_NAMES, VT_STRING, decode_values)


class BlockSearch:
    def __init__(self, part, block_idx: int):
        self.part = part
        self.block_idx = block_idx
        self.nrows = part.block_rows(block_idx)
        self.stream_id = part.block_stream_id(block_idx)
        self.stream_tags_str = part.block_tags(block_idx)
        self._timestamps: np.ndarray | None = None
        self._columns: dict[str, EncodedColumn | None] = {}
        self._values: dict[str, list[str]] = {}
        self._consts: dict[str, str] | None = None

    # ---- lazy reads ----
    def timestamps(self) -> np.ndarray:
        if self._timestamps is None:
            self._timestamps = self.part.block_timestamps(self.block_idx)
        return self._timestamps

    def consts(self) -> dict[str, str]:
        if self._consts is None:
            self._consts = dict(self.part.block_consts(self.block_idx))
        return self._consts

    def column(self, name: str) -> EncodedColumn | None:
        if name not in self._columns:
            self._columns[name] = self.part.block_column(self.block_idx, name)
        return self._columns[name]

    def column_meta(self, name: str) -> dict | None:
        return self.part.block_column_meta(self.block_idx, name)

    def bloom(self, name: str) -> np.ndarray | None:
        return self.part.block_column_bloom(self.block_idx, name)

    def column_names(self) -> list[str]:
        names = list(self.consts().keys())
        names.extend(self.part.block_col_names(self.block_idx))
        return names

    def has_column(self, name: str) -> bool:
        if name in ("_time", "_stream", "_stream_id"):
            return True
        return name in self.consts() or \
            self.part.block_column_meta(self.block_idx, name) is not None

    def value_type_name(self, name: str) -> str:
        """Column type name for value_type() filtering."""
        if name in self.consts():
            return "const"
        meta = self.column_meta(name)
        if meta is None:
            return ""
        return VT_NAMES[meta["t"]]

    def values(self, name: str) -> list[str]:
        """Decoded string values for a column (virtual columns included)."""
        vals = self._values.get(name)
        if vals is not None:
            return vals
        if name == "_time":
            from .block_result import format_rfc3339
            vals = [format_rfc3339(t) for t in self.timestamps().tolist()]
        elif name == "_stream":
            vals = [self.stream_tags_str] * self.nrows
        elif name == "_stream_id":
            vals = [self.stream_id.as_string()] * self.nrows
        else:
            c = self.consts().get(name)
            if c is not None:
                vals = [c] * self.nrows
            else:
                col = self.column(name)
                if col is None:
                    vals = [""] * self.nrows
                else:
                    vals = col.to_strings(self.nrows)
        self._values[name] = vals
        return vals


def new_bitmap(nrows: int) -> np.ndarray:
    return np.ones(nrows, dtype=bool)


def visit_values(bs: BlockSearch, name: str, bm: np.ndarray, pred) -> None:
    """AND pred(value) into bm, evaluated only on currently-set bits.

    Mirrors the reference visitValues pattern (filter_phrase.go:291-299):
    dict columns evaluate the predicate once per dict entry, const/missing
    columns once total.
    """
    if not bm.any():
        return
    if name in ("_time", "_stream", "_stream_id"):
        vals = bs.values(name)
        _apply_pred(vals, bm, pred)
        return
    c = bs.consts().get(name)
    if c is not None:
        if not pred(c):
            bm[:] = False
        return
    col = bs.column(name)
    if col is None:
        if not pred(""):
            bm[:] = False
        return
    if col.vtype == VT_DICT:
        lut = np.fromiter((pred(v) for v in col.dict_values), dtype=bool,
                          count=len(col.dict_values))
        bm &= lut[col.ids]
        return
    _apply_pred(col.to_strings(bs.nrows), bm, pred)


def _apply_pred(vals: list[str], bm: np.ndarray, pred) -> None:
    for i in np.nonzero(bm)[0]:
        if not pred(vals[i]):
            bm[i] = False
