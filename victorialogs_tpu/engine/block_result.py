"""Columnar result batches flowing through the pipe pipeline.

The CPU analogue of the reference blockResult (lib/logstorage/
block_result.go): a batch of rows with lazily-materialized columns.  Straight
from storage it wraps a BlockSearch + selected-row indices (columns decode on
demand and are filtered through the selection); after transforming pipes it
is a plain dict of equal-length string lists.
"""

from __future__ import annotations

import numpy as np

from .block_search import BlockSearch

NS = 1_000_000_000


def format_rfc3339(ts_ns: int) -> str:
    """Render int64 nanos as RFC3339Nano (UTC): trailing fraction zeros
    trimmed, whole seconds carry no fraction (reference
    marshalTimestampRFC3339NanoString)."""
    from ..storage.values_encoder import format_iso8601
    s = format_iso8601(ts_ns, 9)
    if "." in s:
        head, _, frac = s[:-1].partition(".")
        frac = frac.rstrip("0")
        s = (head + "." + frac if frac else head) + "Z"
    return s


_RFC3339_CACHE: dict[str, int | None] = {}


def parse_rfc3339(s: str) -> int | None:
    """Parse an RFC3339-ish timestamp into int64 nanos; None if invalid."""
    if not s:
        return None
    got = _RFC3339_CACHE.get(s)
    if got is not None or s in _RFC3339_CACHE:
        return got
    v = _parse_rfc3339_uncached(s)
    if len(_RFC3339_CACHE) > 4096:
        _RFC3339_CACHE.clear()
    _RFC3339_CACHE[s] = v
    return v


def _parse_rfc3339_uncached(s: str) -> int | None:
    from ..logsql.duration import PARTIAL_RFC3339_RE
    m = PARTIAL_RFC3339_RE.match(s)
    if m is None:
        return None
    y, mo, d, h, mi, sec, frac, tz = m.groups()
    from ..storage.values_encoder import _days_from_civil, _days_in_month
    mo_i = int(mo) if mo else 1
    d_i = int(d) if d else 1
    if not (1 <= mo_i <= 12) or not (1 <= d_i <= _days_in_month(int(y), mo_i)):
        return None
    h_i = int(h) if h else 0
    mi_i = int(mi) if mi else 0
    s_i = int(sec) if sec else 0
    if h_i > 23 or mi_i > 59 or s_i > 59:
        return None
    days = _days_from_civil(int(y), mo_i, d_i)
    ns = (days * 86400 + h_i * 3600 + mi_i * 60 + s_i) * NS
    if frac:
        ns += int(frac) * 10 ** (9 - len(frac))
    if tz and tz != "Z":
        sign = 1 if tz[0] == "+" else -1
        tzh = int(tz[1:3])
        tzm = int(tz[-2:])
        ns -= sign * (tzh * 3600 + tzm * 60) * NS
    return ns


# ---- columnar emit helpers ----
#
# An emit column is a kind-tagged tuple (native.emit_ndjson_native):
#   (0, arena uint8[], offsets int64[n], lengths int64[n])  bytes
#   (1, ts int64[n])            RFC3339Nano timestamps (_time)
#   (2, ts int64[n], frac_w)    ISO8601, fixed fractional width
#   (3, nums int64[n])          signed decimal
#   (4, nums uint64[n])         unsigned decimal
# Typed kinds hand the storage's native arrays straight to the C
# serializer — timestamp/decimal FORMATTING happens there, so the
# Python side does nothing per row.  Length 0 on kind 0 means "omit
# the field on this row".

def _const_emit_col(v: str, n: int):
    b = v.encode("utf-8")
    return (0, np.frombuffer(b, dtype=np.uint8),
            np.zeros(n, dtype=np.int64),
            np.full(n, len(b), dtype=np.int64))


def _pack_str_column(vals: list):
    """Pack a Python string list (pipe-produced columns, rare encodings)
    into a kind-0 emit column."""
    n = len(vals)
    bvals = [v.encode("utf-8") for v in vals]
    lengths = np.fromiter(map(len, bvals), dtype=np.int64, count=n)
    offsets = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lengths[:-1], out=offsets[1:])
    return (0, np.frombuffer(b"".join(bvals), dtype=np.uint8), offsets,
            lengths)


def _fixed_emit_col(sb: np.ndarray):
    """Kind-0 emit column over a fixed-width ASCII bytes array
    (astype('S...') output: values left-aligned, NUL padded — the
    canonical float strings never contain NUL)."""
    n = sb.shape[0]
    w = sb.dtype.itemsize
    mat = sb.view(np.uint8).reshape(n, w)
    lengths = (mat != 0).sum(axis=1).astype(np.int64)
    return (0, np.ascontiguousarray(mat).reshape(-1),
            np.arange(n, dtype=np.int64) * w, lengths)


# ---- wire columns (cluster typed frames) ----
#
# A wire column is a kind-tagged tuple shipped between cluster nodes
# (server/cluster.py owns the binary framing + negotiation).  It is the
# emit-column contract extended with the two shapes that compress
# better on the wire than their flattened emit form (dict codes + tiny
# value arenas, single-copy consts):
#   (WIRE_STR, arena uint8[], offsets int[n], lengths int[n])  dense
#   (WIRE_TIME, ts int64[n])        native _time nanos
#   (WIRE_ISO, ts int64[n], frac_w) ISO8601, fixed fractional width
#   (WIRE_INT, nums int64[n])
#   (WIRE_UINT, nums uint64[n])
#   (WIRE_DICT, codes uint8[n], values list[str])
#   (WIRE_CONST, value str)
#   (WIRE_FLOAT, nums float64[n])
# WIRE_STR arenas are DENSE (offsets are the cumsum of lengths): the
# encoder never ships unselected bytes of a storage arena.

WIRE_STR = 0
WIRE_TIME = 1
WIRE_ISO = 2
WIRE_INT = 3
WIRE_UINT = 4
WIRE_DICT = 5
WIRE_CONST = 6
WIRE_FLOAT = 7


def _dense_str_triple(arena: np.ndarray, offsets: np.ndarray,
                      lengths: np.ndarray):
    """Repack a (possibly selection-gathered) string triple into a
    dense arena: offsets become the cumsum of lengths and the arena
    holds exactly the selected bytes, in row order."""
    n = int(lengths.shape[0])
    lengths = lengths.astype(np.int64, copy=False)
    total = int(lengths.sum())
    new_off = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lengths[:-1], out=new_off[1:])
    if int(arena.shape[0]) == total and (n == 0 or
                                         np.array_equal(offsets, new_off)):
        return arena, new_off, lengths
    if total == 0:
        return np.empty(0, dtype=np.uint8), new_off, lengths
    idx = np.repeat(offsets.astype(np.int64, copy=False) - new_off,
                    lengths) + np.arange(total, dtype=np.int64)
    return arena[idx], new_off, lengths


def _wire_take(wc, keep: np.ndarray):
    """Row-select one wire column (filter_rows for wire views).  The
    arena of a WIRE_STR column stays whole — only offsets/lengths
    gather — so selection is O(kept rows); re-encoding for the wire
    densifies again."""
    kind = wc[0]
    if kind == WIRE_STR:
        return (kind, wc[1], wc[2][keep], wc[3][keep])
    if kind == WIRE_ISO:
        return (kind, wc[1][keep], wc[2])
    if kind == WIRE_DICT:
        return (kind, wc[1][keep], wc[2])
    if kind == WIRE_CONST:
        return wc
    return (kind, wc[1][keep])


class BlockResult:
    """A batch of result rows with lazily-materialized string columns.

    Invariant: on a block-backed result (_bs set), _cols only ever holds
    CACHE FILLS — the decode of the same storage column that column()
    produced.  Pipes that override or add columns always do so on a
    materialized copy (materialize() drops _bs), so the typed accessors
    below stay valid even after another consumer materialized the same
    column's strings."""

    def __init__(self, nrows: int):
        self.nrows = nrows
        self._cols: dict[str, list[str]] = {}
        self._bs: BlockSearch | None = None
        self._sel: np.ndarray | None = None   # selected row indices into bs
        self._needed: set | None = None       # needed-columns restriction
        # fields-pipe projection (restrict_fields): ordered output names.
        # Unlike _needed (a scan-side hint), this is a HARD projection:
        # names outside it read as "" exactly like the materialized copy
        # the fields pipe used to build, but the block stays attached so
        # the emit path keeps its typed columnar access.
        self._restrict: list[str] | None = None
        self._restrict_set: frozenset | None = None
        # cluster wire view (from_wire): name -> wire column tuple.
        # Like _bs, this is a typed backing store — _cols only ever
        # holds cache fills of the same decode.
        self._wire: dict | None = None
        self._wire_names: list[str] | None = None
        self._ts_list: list[int] | None = None
        self._ts_np: np.ndarray | None = None
        # numeric views of produced columns (e.g. math results): maps
        # name -> (string_list_identity, float64 array).  The view is
        # only honored while _cols[name] IS that exact list object, so
        # any pipe overwriting the column silently invalidates it —
        # no per-pipe bookkeeping needed.
        self._num_cols: dict[str, tuple] = {}

    # timestamps materialize lazily: storage-backed blocks carry the int64
    # array and only build the Python list when a consumer indexes it
    # (stats fast paths read the array directly via timestamps_np())
    @property
    def timestamps(self) -> list | None:
        if self._ts_list is None and self._ts_np is not None:
            self._ts_list = self._ts_np.tolist()
        return self._ts_list

    @timestamps.setter
    def timestamps(self, v) -> None:
        self._ts_list = v
        self._ts_np = None

    def timestamps_np(self) -> np.ndarray | None:
        if self._ts_np is None and self._ts_list is not None:
            self._ts_np = np.asarray(self._ts_list, dtype=np.int64)
        return self._ts_np

    def native_time_keys(self) -> np.ndarray | None:
        """int64 nanos of the DISPLAYED `_time` column, or None when a
        pipe may have rewritten it (sinks that sort on _time — the tail
        loop — use this instead of re-parsing rendered strings).  Valid
        for block-backed results (displayed _time IS the storage
        timestamps unless materialized) and wire views carrying a
        native WIRE_TIME column."""
        if self._restrict_set is not None and \
                "_time" not in self._restrict_set:
            return None
        if self._bs is not None:
            return self._ts_np
        if self._wire is not None:
            wc = self._wire.get("_time")
            if wc is not None and wc[0] == WIRE_TIME:
                return wc[1]
        return None

    # ---- constructors ----
    @staticmethod
    def from_block_search(bs: BlockSearch, bm: np.ndarray,
                          needed: set | None = None) -> "BlockResult":
        """needed: optional needed-columns set from the pipe chain — when
        given (and not {"*"}), column_names()/rows() only enumerate those,
        so unreferenced columns are never decoded."""
        sel = np.nonzero(bm)[0]
        br = BlockResult(int(sel.shape[0]))
        br._bs = bs
        br._sel = sel
        if needed is not None and "*" in needed:
            needed = None
        br._needed = needed
        br._ts_np = bs.timestamps()[sel]
        return br

    @staticmethod
    def from_columns(cols: dict[str, list[str]],
                     timestamps: list[int] | None = None) -> "BlockResult":
        n = len(next(iter(cols.values()))) if cols else 0
        br = BlockResult(n)
        br._cols = dict(cols)
        br.timestamps = timestamps
        return br

    @staticmethod
    def from_wire(names: list[str], wcols: dict, nrows: int,
                  ts_np: np.ndarray | None = None) -> "BlockResult":
        """Arena-backed view over decoded wire columns (cluster typed
        frames): string columns stay packed arenas, typed columns stay
        native arrays, so the frontend's emit path feeds
        vl_emit_ndjson without ever materializing per-row strings.
        Pipe consumers that DO want strings decode lazily per column
        through column(), exactly like block-backed results."""
        br = BlockResult(nrows)
        br._wire = wcols
        br._wire_names = list(names)
        br._ts_np = ts_np
        return br

    # ---- access ----
    def column(self, name: str) -> list[str]:
        if self._restrict_set is not None and \
                name not in self._restrict_set:
            # projected-out field: absent, like the materialized copy
            return [""] * self.nrows
        vals = self._cols.get(name)
        if vals is not None:
            return vals
        if self._wire is not None:
            wc = self._wire.get(name)
            vals = self._wire_strings(wc) if wc is not None \
                else [""] * self.nrows
        elif self._bs is not None and (name in ("_time", "_stream",
                                                "_stream_id")
                                       or self._bs.has_column(name)):
            full = self._bs.values(name)
            vals = [full[i] for i in self._sel.tolist()]
        else:
            vals = [""] * self.nrows
        self._cols[name] = vals
        return vals

    def _wire_strings(self, wc) -> list[str]:
        """Decode one wire column to per-row strings — the SAME decodes
        the storage node's own column() would have produced
        (values_encoder.decode_values / block_search.values), so local
        pipes see identical values on both sides of the wire."""
        kind = wc[0]
        if kind == WIRE_STR:
            buf = wc[1].tobytes()
            return [buf[o:o + l].decode("utf-8", "replace")
                    for o, l in zip(wc[2].tolist(), wc[3].tolist())]
        if kind == WIRE_TIME:
            return [format_rfc3339(t) for t in wc[1].tolist()]
        if kind == WIRE_ISO:
            from ..storage.values_encoder import format_iso8601
            return [format_iso8601(t, wc[2]) for t in wc[1].tolist()]
        if kind in (WIRE_INT, WIRE_UINT):
            return wc[1].astype("U20").tolist()
        if kind == WIRE_DICT:
            dv = wc[2]
            return [dv[i] for i in wc[1].tolist()]
        if kind == WIRE_CONST:
            return [wc[1]] * self.nrows
        if kind == WIRE_FLOAT:
            from ..storage.values_encoder import _format_floats
            return _format_floats(wc[1]).tolist()
        raise ValueError(f"unknown wire column kind {kind}")

    def has_column(self, name: str) -> bool:
        if self._restrict_set is not None:
            return name in self._restrict_set
        if name in self._cols:
            return True
        if self._wire is not None:
            return name in self._wire
        return self._bs is not None and self._bs.has_column(name)

    def numeric_column(self, name: str):
        """float64 view of a storage-typed numeric column (uint/int/float),
        or None — lets stats skip per-row string parsing (the reference
        keeps blockResult columns type-encoded for the same reason —
        block_result.go:26-63)."""
        if self._restrict_set is not None and \
                name not in self._restrict_set:
            return None
        got = self._num_cols.get(name)
        if got is not None and self._cols.get(name) is got[0]:
            return got[1]
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is not None and wc[0] in (WIRE_INT, WIRE_UINT,
                                            WIRE_FLOAT):
                return wc[1].astype(np.float64)
            return None
        if self._bs is None:
            return None
        from ..storage.values_encoder import (VT_FLOAT64, VT_INT64,
                                              VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64)
        if name in self._bs.consts() or name in ("_time", "_stream",
                                                 "_stream_id"):
            return None
        col = self._bs.column(name)
        if col is None or col.vtype not in (VT_UINT8, VT_UINT16, VT_UINT32,
                                            VT_UINT64, VT_INT64,
                                            VT_FLOAT64):
            return None
        return col.nums[self._sel].astype(np.float64)

    def typed_numeric(self, name: str):
        """(selected values array, is_int) for a uint/int/float column, or
        None.  Unlike numeric_column, int columns keep their native
        integer dtype so consumers can regenerate the exact canonical
        stored strings (round-trip encodings — values_encoder.py) without
        ever materializing a Python string list
        (block_result.go:2149-2199)."""
        if self._restrict_set is not None and \
                name not in self._restrict_set:
            return None
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is None:
                return None
            if wc[0] in (WIRE_INT, WIRE_UINT):
                return wc[1], True
            if wc[0] == WIRE_FLOAT:
                return wc[1], False
            return None
        if self._bs is None:
            return None
        from ..storage.values_encoder import (VT_FLOAT64, VT_INT64,
                                              VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64)
        if name in self._bs.consts() or name in ("_time", "_stream",
                                                 "_stream_id"):
            return None
        col = self._bs.column(name)
        if col is None:
            return None
        if col.vtype in (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64,
                         VT_INT64):
            # native dtype: an int64 cast would wrap uint64 values >= 2**63
            return col.nums[self._sel], True
        if col.vtype == VT_FLOAT64:
            return col.nums[self._sel], False
        return None

    def const_value(self, name: str) -> str | None:
        """The single value of a column KNOWN constant across this block
        (const columns; _stream/_stream_id are per-block constants by
        construction), or None."""
        if self.nrows == 0 or (self._restrict_set is not None
                               and name not in self._restrict_set):
            return None
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is not None and wc[0] == WIRE_CONST:
                return wc[1]
            return None
        if self._bs is None:
            return None
        c = self._bs.consts().get(name)
        if c is not None:
            return c
        if name == "_stream":
            return self._bs.stream_tags_str
        if name == "_stream_id":
            return self._bs.stream_id.as_string()
        return None

    def dict_value_counts(self, name: str):
        """[(value, count)] over the selected rows of a const/dict
        column, or None — group-by/top/uniq count through the stored
        codes instead of materializing strings."""
        cv = self.const_value(name)
        if cv is not None:
            return [(cv, self.nrows)]
        dc = self.dict_column(name)
        if dc is None:
            return None
        ids, dvals = dc
        binc = np.bincount(ids, minlength=len(dvals))
        return [(dvals[j], int(binc[j])) for j in np.nonzero(binc)[0]]

    def dict_column(self, name: str):
        """(selected dict ids uint8, dict value strings) for a
        dict-encoded column, or None — lets group-by factorize through
        the stored codes without materializing a per-row string list."""
        if self._restrict_set is not None and \
                name not in self._restrict_set:
            return None
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is not None and wc[0] == WIRE_DICT:
                return wc[1], wc[2]
            return None
        if self._bs is None:
            return None
        from ..storage.values_encoder import VT_DICT
        if name in self._bs.consts() or name in ("_time", "_stream",
                                                 "_stream_id"):
            return None
        col = self._bs.column(name)
        if col is None or col.vtype != VT_DICT:
            return None
        return col.ids[self._sel], col.dict_values

    def header_min_max(self, name: str):
        """(min, max) of a numeric column from the BLOCK HEADER — no
        column payload read/decode (reference per-column min/max skips,
        block_result.go:26-63).  None for non-numeric/absent columns."""
        if self._bs is None or (self._restrict_set is not None
                                and name not in self._restrict_set):
            return None
        from ..storage.values_encoder import (VT_FLOAT64, VT_INT64,
                                              VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64)
        meta = self._bs.column_meta(name)
        if meta is None or meta.get("t") not in (
                VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64, VT_INT64,
                VT_FLOAT64):
            return None
        mn, mx = meta.get("min"), meta.get("max")
        if mn is None or mx is None:
            return None
        return float(mn), float(mx)

    def column_names(self) -> list[str]:
        if self._restrict is not None:
            return list(self._restrict)
        names: dict[str, None] = {}
        if self._wire is not None:
            for n in self._wire_names:
                names[n] = None
        elif self._bs is not None:
            if self._needed is None:
                names["_time"] = None
                names["_stream"] = None
                names["_stream_id"] = None
                for n in self._bs.column_names():
                    names[n] = None
            else:
                for n in ("_time", "_stream", "_stream_id"):
                    if n in self._needed:
                        names[n] = None
                for n in self._bs.column_names():
                    if n in self._needed:
                        names[n] = None
        for n in self._cols:
            names[n] = None
        return list(names)

    def materialize(self, fields: list[str] | None = None) -> "BlockResult":
        """Detach from the underlying block (copy out the needed columns)."""
        names = fields if fields is not None else self.column_names()
        cols = {n: self.column(n) for n in names}
        out = BlockResult.from_columns(cols)
        out._ts_np = self._ts_np
        out._ts_list = self._ts_list
        for nm, (ref, arr) in self._num_cols.items():
            if out._cols.get(nm) is ref:
                out._num_cols[nm] = (ref, arr)
        # a needed-columns restriction can leave zero columns while rows
        # still exist (e.g. copy/rename rebuilding them); keep the count
        out.nrows = self.nrows
        return out

    def restrict_fields(self, fields: list[str]) -> "BlockResult":
        """Project to exactly `fields` (in order) WITHOUT detaching from
        the block: the semantic twin of materialize(fields) — names
        outside the projection read as "" — but typed columnar access
        (emit_columns, dict/numeric fast paths) survives for the names
        kept.  The fields/delete pipes use this so storage-backed rows
        reach the NDJSON emit sink without a per-row materialization."""
        # dedupe keeping first position: materialize's dict comprehension
        # collapsed `fields a, a` the same way, and duplicate names must
        # not become duplicate JSON keys on the emit path
        fields = list(dict.fromkeys(fields))
        if self._bs is None and self._wire is None:
            return self.materialize(fields)
        br = BlockResult(self.nrows)
        br._bs = self._bs
        br._sel = self._sel
        br._wire = self._wire
        br._wire_names = self._wire_names
        br._restrict = fields
        # chained projections only ever narrow: a name re-added by a
        # later `fields` pipe after being dropped still reads ""
        br._restrict_set = frozenset(br._restrict) \
            if self._restrict_set is None \
            else frozenset(br._restrict) & self._restrict_set
        br._ts_np = self._ts_np
        br._ts_list = self._ts_list
        for n in br._restrict:
            vals = self._cols.get(n)
            if vals is not None:       # cache fills only (class invariant)
                br._cols[n] = vals
                got = self._num_cols.get(n)
                if got is not None and got[0] is vals:
                    br._num_cols[n] = got
        return br

    def filter_rows(self, mask: np.ndarray) -> "BlockResult":
        keep = np.nonzero(mask)[0]
        br = BlockResult(int(keep.shape[0]))
        br._needed = self._needed
        br._restrict = self._restrict
        br._restrict_set = self._restrict_set
        if self._wire is not None:
            br._wire = {n: _wire_take(wc, keep)
                        for n, wc in self._wire.items()}
            br._wire_names = list(self._wire_names)
            kl = keep.tolist()
            for n, vals in self._cols.items():
                br._cols[n] = [vals[i] for i in kl]
        elif self._bs is not None and not self._cols:
            br._bs = self._bs
            br._sel = self._sel[keep]
        else:
            kl = keep.tolist()
            for n, vals in self._cols.items():
                br._cols[n] = [vals[i] for i in kl]
            if self._bs is not None:
                br._bs = self._bs
                br._sel = self._sel[keep]
        if self._ts_np is not None:
            br._ts_np = self._ts_np[keep]
        elif self._ts_list is not None:
            br._ts_list = [self._ts_list[i] for i in keep.tolist()]
        for nm, (ref, arr) in self._num_cols.items():
            if br._cols.get(nm) is not None and \
                    self._cols.get(nm) is ref:
                # pair the view with the freshly sliced list
                br._num_cols[nm] = (br._cols[nm], arr[keep])
        return br

    def rows(self, fields: list[str] | None = None) -> list[dict]:
        """Materialize as row dicts (empty values omitted, like the API).

        Bulk form: one zip pass over the column lists instead of a
        per-row per-column index.  This is the dict-rows convenience /
        oracle — hot NDJSON sinks bypass it entirely via emit_columns()
        (engine/emit.py)."""
        names = fields if fields is not None else self.column_names()
        if not names:
            # vlint: allow-per-row-emit(zero-column edge: {} rows ARE the output)
            return [{} for _ in range(self.nrows)]
        cols = [self.column(n) for n in names]
        out = []
        append = out.append
        for tup in zip(*cols):
            # vlint: allow-per-row-emit(dict-rows oracle; hot sinks use emit_columns)
            append({n: v for n, v in zip(names, tup) if v != ""})
        return out

    # ---- columnar emit (engine/emit.py consumes this) ----

    def emit_columns(self, fields: list[str] | None = None):
        """Bulk selected-row materialization for the NDJSON emit path:
        (names, [kind-tagged emit column per name]) — per-column
        vectorized gathers from the decoded arenas/offset arrays for
        exactly the hit rows, no intermediate per-row Python objects
        (the reference's lazy-column blockResult discipline).  Typed
        columns (timestamps, ints) pass their native int arrays through
        untouched; the C serializer formats them (see the emit-column
        helpers above for the kind encoding)."""
        names = fields if fields is not None else self.column_names()
        return names, [self._emit_column(n) for n in names]

    def _emit_column(self, name: str):
        n = self.nrows
        if n == 0 or (self._restrict_set is not None
                      and name not in self._restrict_set):
            return _const_emit_col("", n)
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is None:
                return _const_emit_col("", n)
            return self._wire_emit_col(wc)
        if self._bs is None:
            return _pack_str_column(self._cols.get(name) or [""] * n)
        if name == "_time":
            if self._ts_np is not None:
                return (1, self._ts_np)
            return _pack_str_column(self.column(name))
        cv = self.const_value(name)    # consts + _stream/_stream_id
        if cv is not None:
            return _const_emit_col(cv, n)
        col = self._bs.column(name)
        if col is None:
            return _const_emit_col("", n)
        from ..storage.values_encoder import (VT_CONST, VT_DICT,
                                              VT_FLOAT64, VT_INT64,
                                              VT_STRING,
                                              VT_TIMESTAMP_ISO8601,
                                              VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64,
                                              _format_floats)
        vt = col.vtype
        if vt == VT_STRING:
            # zero copy: the stored arena IS the emit arena; only the
            # per-row offset/length vectors gather through the selection
            return (0, col.arena, col.offsets[self._sel],
                    col.lengths[self._sel])
        if vt == VT_DICT:
            # pack the (<=8) dict values once, gather through the codes
            _k, arena, doffs, dlens = _pack_str_column(col.dict_values)
            ids = col.ids[self._sel]
            return 0, arena, doffs[ids], dlens[ids]
        if vt == VT_CONST:
            return _const_emit_col(col.const_value, n)
        if vt == VT_INT64:
            return (3, self._sel_nums(col))
        if vt in (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64):
            return (4, self._sel_nums(col).astype(np.uint64))
        if vt == VT_FLOAT64:
            # floats keep the numpy canonical-repr formatting: the C
            # side can't cheaply reproduce Python's shortest round-trip
            return _fixed_emit_col(
                _format_floats(self._sel_nums(col)).astype("S32"))
        if vt == VT_TIMESTAMP_ISO8601:
            return (2, self._sel_nums(col), col.iso_frac_w)
        # VT_IPV4 and anything future: decode cache + packed gather
        full = col.to_strings(self._bs.nrows)
        return _pack_str_column([full[i] for i in self._sel.tolist()])

    def _sel_nums(self, col) -> np.ndarray:
        return col.nums[self._sel]

    def _wire_emit_col(self, wc):
        """One decoded wire column as an emit column: typed kinds map
        1:1 (the C serializer formats them), string arenas pass through
        with int64 offset views, dict codes gather through their packed
        value arena — the same shapes the local emit path produces, so
        the scatter-gather sink is arena-copy + native emit end to
        end."""
        kind = wc[0]
        if kind == WIRE_STR:
            return (0, wc[1], wc[2].astype(np.int64, copy=False),
                    wc[3].astype(np.int64, copy=False))
        if kind == WIRE_TIME:
            return (1, wc[1])
        if kind == WIRE_ISO:
            return (2, wc[1], wc[2])
        if kind == WIRE_INT:
            return (3, wc[1])
        if kind == WIRE_UINT:
            return (4, wc[1])
        if kind == WIRE_DICT:
            _k, arena, doffs, dlens = _pack_str_column(wc[2])
            ids = wc[1]
            return 0, arena, doffs[ids], dlens[ids]
        if kind == WIRE_CONST:
            return _const_emit_col(wc[1], self.nrows)
        if kind == WIRE_FLOAT:
            from ..storage.values_encoder import _format_floats
            return _fixed_emit_col(_format_floats(wc[1]).astype("S32"))
        raise ValueError(f"unknown wire column kind {kind}")

    # ---- columnar wire encode (server/cluster.py consumes this) ----

    def wire_columns(self, fields: list[str] | None = None):
        """Bulk selected-row materialization for the cluster wire path:
        (names, [wire column per name]) — the emit-column discipline
        with dict/const columns kept in their compact stored shapes.
        Storage nodes serialize internal-select results straight from
        this with zero row materialization; BlockResult.from_wire is
        the decode-side twin."""
        names = fields if fields is not None else self.column_names()
        return names, [self._wire_column(n) for n in names]

    def _wire_column(self, name: str):
        n = self.nrows
        if n == 0 or (self._restrict_set is not None
                      and name not in self._restrict_set):
            return (WIRE_CONST, "")
        if self._wire is not None:
            wc = self._wire.get(name)
            if wc is None:
                return (WIRE_CONST, "")
            if wc[0] == WIRE_STR:
                return (WIRE_STR,) + _dense_str_triple(wc[1], wc[2],
                                                       wc[3])
            return wc
        if self._bs is None:
            vals = self._cols.get(name)
            if vals is None:
                return (WIRE_CONST, "")
            return (WIRE_STR,) + _pack_str_column(vals)[1:]
        if name == "_time" and self._ts_np is not None:
            return (WIRE_TIME, self._ts_np)
        cv = self.const_value(name)    # consts + _stream/_stream_id
        if cv is not None:
            return (WIRE_CONST, cv)
        col = self._bs.column(name)
        if col is None:
            return (WIRE_CONST, "")
        from ..storage.values_encoder import (VT_CONST, VT_DICT,
                                              VT_FLOAT64, VT_INT64,
                                              VT_STRING,
                                              VT_TIMESTAMP_ISO8601,
                                              VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64)
        vt = col.vtype
        if vt == VT_STRING:
            return (WIRE_STR,) + _dense_str_triple(
                col.arena, col.offsets[self._sel],
                col.lengths[self._sel])
        if vt == VT_DICT:
            return (WIRE_DICT, col.ids[self._sel], col.dict_values)
        if vt == VT_CONST:
            return (WIRE_CONST, col.const_value)
        if vt == VT_INT64:
            return (WIRE_INT, self._sel_nums(col))
        if vt in (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64):
            return (WIRE_UINT, self._sel_nums(col).astype(np.uint64))
        if vt == VT_FLOAT64:
            # floats ship native f64: the decoder re-renders via the
            # same numpy canonical-repr helper, so strings round-trip
            return (WIRE_FLOAT,
                    self._sel_nums(col).astype(np.float64, copy=False))
        if vt == VT_TIMESTAMP_ISO8601:
            return (WIRE_ISO, self._sel_nums(col), col.iso_frac_w)
        # VT_IPV4 and anything future: decode cache + packed gather
        full = col.to_strings(self._bs.nrows)
        return (WIRE_STR,) + _pack_str_column(
            [full[i] for i in self._sel.tolist()])[1:]
