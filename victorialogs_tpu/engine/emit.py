"""Columnar NDJSON emit: BlockResult -> response bytes, no per-row dicts.

PR 4's trace attribution showed the harvest tail is emit-dominated: the
device answers in ~3 ms while the host spends tens of ms building a dict
per row and calling json.dumps per row (PERF.md "vltrace").  This module
is the columnar replacement for that hot path:

    BlockResult.emit_columns()  — bulk selected-row materialization:
        one (arena, offsets, lengths) byte triple per output column,
        gathered vectorized from the storage arenas (zero-copy for
        string columns, numpy-formatted for numeric/dict/time columns);
    native.vl_emit_ndjson       — columns in, escaped NDJSON bytes out.

Output bytes are BIT-IDENTICAL to the per-row path
(json.dumps(row, ensure_ascii=False, separators=(",", ":")) over
rows() dicts): same key order (column order), same escapes, empty
values omitted, "{}" for all-empty rows.  tests/test_emit.py is the
differential suite; `VL_NATIVE_EMIT=0` is the kill-switch that forces
the per-row fallback (which is also the parity oracle).

The same columnar contract now crosses the cluster seam: storage nodes
ship typed wire frames (BlockResult.wire_columns — server/cluster.py)
and frontends decode them into arena-backed views (from_wire) whose
emit_columns() feeds this module directly, so scatter-gather NDJSON is
arena-copy + native emit end to end.  tests/test_wire.py is that
path's differential suite.
"""

from __future__ import annotations

import json
from .. import config

from ..native import available as native_available
from ..native import emit_ndjson_native

# pre-quoted b'"key":' tokens: key escaping is delegated to Python's own
# json.dumps, so native output can't diverge on exotic field names
_KEY_TOKENS: dict[str, bytes] = {}


def _key_token(name: str) -> bytes:
    tok = _KEY_TOKENS.get(name)
    if tok is None:
        if len(_KEY_TOKENS) > 4096:
            _KEY_TOKENS.clear()
        tok = (json.dumps(name, ensure_ascii=False) + ":").encode("utf-8")
        _KEY_TOKENS[name] = tok
    return tok


def native_emit_enabled() -> bool:
    """VL_NATIVE_EMIT=0 kills the native serializer (parity debugging)."""
    return config.env_flag("VL_NATIVE_EMIT")


def ndjson_block(br, fields: list[str] | None = None) -> bytes:
    """One result block as NDJSON bytes (one line per row, trailing
    newline); b"" for empty blocks."""
    if br.nrows == 0:
        return b""
    # probe the lib BEFORE the columnar gather: on toolchain-less hosts
    # emit_columns work would be thrown away for the per-row path every
    # block (available() is a cached flag after first load)
    if native_emit_enabled() and native_available():
        names, cols = br.emit_columns(fields)
        data = emit_ndjson_native([_key_token(n) for n in names], cols,
                                  br.nrows)
        if data is not None:
            return data
    return ndjson_block_py(br, fields)


# vlint: allow-per-row-emit(VL_NATIVE_EMIT=0 fallback + parity oracle)
def ndjson_block_py(br, fields: list[str] | None = None) -> bytes:
    """Per-row fallback: the exact pre-columnar emit path."""
    out = []
    for row in br.rows(fields):
        out.append(json.dumps(row, ensure_ascii=False,
                              separators=(",", ":")))
    out.append("")                     # trailing newline
    return "\n".join(out).encode("utf-8")
