"""Query execution: partition/part/block scheduling + pipe chain driving.

The CPU analogue of the reference's storage_search.go: RunQuery materializes
subqueries, extracts the global time range from the filter tree, resolves
`{stream}` filters against each partition's index, schedules surviving blocks
through the filter tree, and feeds resulting batches through the pipe
processor chain with per-pipe cancellation (storage_search.go:102-185,
1035-1121).

The per-block scan dispatches to the TPU runner when enabled (tpu/batch.py);
this module stays the correctness oracle and the fallback path.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass

import numpy as np

from ..logsql.filters import (Filter, FilterAnd, FilterIn, FilterContainsAll,
                              FilterContainsAny, FilterNone, FilterNoop,
                              FilterNot, FilterOr, FilterStream, FilterTime)
from ..obs import activity, events, tracing
from ..logsql.parser import MAX_TS, MIN_TS, Query, parse_query
from ..logsql.pipes import Processor, SinkProcessor
from ..storage.log_rows import TenantID
from .block_result import BlockResult
from .block_search import BlockSearch, new_bitmap


@dataclass
class SearchContext:
    partition: object
    tenants: tuple


class QueryCancelled(Exception):
    pass


class QueryTimeoutError(Exception):
    """Raised when a query exceeds its deadline (reference
    -search.maxQueryDuration — app/vlselect/main.go:133-150)."""


class _CancelAwareHead:
    """Processor-chain head facade that folds the active-query
    registry's cancel flag (cancel_query / client-disconnect abandon —
    obs/activity.py) into is_done(): the scan loops already treat a
    done head as QueryCancelled, so an external cancel drains the
    device pipeline's in-flight window without downstream writes
    (tpu/pipeline.py PR 3 semantics) and stops the serial walk at its
    next block."""

    __slots__ = ("_head", "_act")

    def __init__(self, head, act):
        self._head = head
        self._act = act

    def write_block(self, br) -> None:
        self._head.write_block(br)

    def absorb_partials(self, key, states) -> None:
        self._head.absorb_partials(key, states)

    def flush(self) -> None:
        self._head.flush()

    def is_done(self) -> bool:
        return self._act.is_cancelled() or self._head.is_done()


def build_processor_chain(pipes: list, write_fn) -> Processor:
    pp: Processor = SinkProcessor(write_fn)
    for pipe in reversed(pipes):
        pp = pipe.make_processor(pp)
    return pp


def _iter_subquery_filters(f: Filter):
    if isinstance(f, (FilterIn, FilterContainsAll, FilterContainsAny)):
        if f.subquery is not None:
            yield f
    elif isinstance(f, (FilterAnd, FilterOr)):
        for sub in f.filters:
            yield from _iter_subquery_filters(sub)
    elif isinstance(f, FilterNot):
        yield from _iter_subquery_filters(f.inner)


def _run_single_column_subquery(storage, tenants, sub, runner=None
                                ) -> list[str]:
    """Run a subquery that must yield exactly one result column (the
    reference errors on multi-column in() subqueries too)."""
    values: list[str] = []
    col_name: list = [None]

    def sink(br: BlockResult):
        if br._bs is not None and br._restrict is None:
            # raw storage blocks (no fields projection): require an
            # explicit `| fields x` pipe
            raise ValueError(
                "in(<subquery>) must narrow its output to one column, "
                "e.g. `... | fields x`")
        names = br.column_names()
        if len(names) != 1:
            raise ValueError(
                f"in(<subquery>) must yield exactly one column, got "
                f"{names!r}")
        if col_name[0] is None:
            col_name[0] = names[0]
        elif col_name[0] != names[0]:
            raise ValueError(
                f"in(<subquery>) yielded inconsistent columns "
                f"{col_name[0]!r} vs {names[0]!r}")
        values.extend(br.column(names[0]))
    run_query(storage, tenants, sub, write_block=sink, runner=runner)
    return values


def init_subqueries(storage, tenants, q: Query, runner=None,
                    detach: bool = False) -> None:
    """Materialize in(<subquery>)-style filters (reference
    storage_search.go:530-553).

    detach=True drops the subquery after materialization so to_string()
    renders the literal value list — the cluster front uses this to
    resolve subqueries over the WHOLE cluster once and ship plain in(...)
    filters to the storage nodes (reference initFilterInValues)."""
    from ..logsql.pipes import PipeWhere
    subfilters = list(_iter_subquery_filters(q.filter))
    for p in q.pipes:
        if isinstance(p, PipeWhere):
            subfilters.extend(_iter_subquery_filters(p.filter))
    for f in subfilters:
        f.set_values(_run_single_column_subquery(storage, tenants,
                                                 f.subquery, runner=runner))
        if detach:
            f.subquery = None


def _collect_stream_filters(f: Filter, out: list) -> None:
    """Stream filters on the top-level AND path (usable for block pruning)."""
    if isinstance(f, FilterStream):
        out.append(f)
    elif isinstance(f, FilterAnd):
        for sub in f.filters:
            _collect_stream_filters(sub, out)


def run_query(storage, tenants, q: Query | str, write_block=None,
              timestamp: int | None = None, runner=None,
              deadline: float | None = None) -> None:
    """Execute a LogsQL query; write_block(BlockResult) receives results.

    write_block is the COLUMNAR sink protocol: blocks arrive with their
    storage backing attached whenever the pipe chain allows (the fields/
    delete pipes project without materializing), so sinks that serialize
    (server/vlselect.py NDJSON emit) go straight from the harvested
    bitmaps to response bytes via BlockResult.emit_columns() /
    engine.emit.ndjson_block() — rows never become per-row dicts on that
    path.  Dict-rows consumers keep using br.rows().

    runner: optional TPU runner (tpu/batch.py BatchRunner) — when given,
    block filtering dispatches to the device, one dispatch per leaf per
    part.
    deadline: monotonic-clock limit; past it the query fails with
    QueryTimeoutError (reference -search.maxQueryDuration).
    """
    if isinstance(q, str):
        q = parse_query(q, timestamp)
    if isinstance(tenants, TenantID):
        tenants = [tenants]
    tenants = tuple(tenants)

    # self-telemetry recursion guard: a query AGAINST the reserved
    # system tenant must not feed the journal it is reading.  Queries
    # registered in the activity registry are suppressed ambiently
    # (events.emit checks the record's tenant on EVERY worker thread —
    # the record propagates into partition/pool workers via
    # use_activity).  A bare engine-level entry with no record gets
    # both halves here: a thread-local guard for this thread's extent
    # AND a registered system-tenant record, so fan-out workers —
    # which re-enter the record but not the thread-local — are
    # suppressed too.
    if not events.in_guard() and \
            not activity.current_activity().enabled and \
            any(activity.tenant_str(t) == events.SYSTEM_TENANT
                for t in tenants):
        with events.guarded(), \
                activity.track("run_query", q.to_string(), tenants):
            _run_query_guarded(storage, tenants, q, write_block,
                               timestamp, runner, deadline)
        return

    _run_query_guarded(storage, tenants, q, write_block, timestamp,
                       runner, deadline)


def _run_query_guarded(storage, tenants, q, write_block, timestamp,
                       runner, deadline) -> None:
    if hasattr(storage, "net_run_query"):
        # cluster mode: storage is a NetSelectStorage — scatter-gather the
        # query over the storage nodes (server/cluster.py)
        storage.net_run_query(list(tenants), q, write_block=write_block,
                              timestamp=timestamp, deadline=deadline)
        return

    # continuous plan-time pricing (obs/explain.py): claim the record's
    # priced slot BEFORE subqueries materialize — an in(<subquery>)
    # executes through this same record and must not publish ITS
    # prediction as the outer query's
    from ..obs import explain
    act0 = activity.current_activity()
    price = runner is not None and act0.enabled and \
        explain.pricing_enabled() and not act0.counter("priced")
    if price:
        act0.set("priced", 1)

    init_subqueries(storage, tenants, q, runner=runner)
    # storage-backed pipes (join/union/stream_context) get their query hook
    for p in q.pipes:
        if hasattr(p, "init_with_storage"):
            p.init_with_storage(storage, tenants, runner)

    if price:
        # the same header walk _scan_parts repeats in a moment, priced
        # against the live cost-model EWMAs: predicted_* land next to
        # the actuals in the query_done journal event, and
        # sched/admission can weigh predicted_duration_s against a
        # request deadline in a follow-up
        explain.price_into_activity(storage, tenants, q, runner, act0)
    min_ts, max_ts = q.get_time_range()

    # rate()/rate_sum() divide by the time-filter range (reference
    # Query.initStatsRateFuncsFromTimeFilter — parser.go:1218-1224)
    if min_ts != MIN_TS and max_ts != MAX_TS:
        from ..logsql.pipes import PipeStats
        step_seconds = (max_ts - min_ts + 1) / 1e9
        for p in q.pipes:
            if isinstance(p, PipeStats):
                for fn in p.funcs:
                    if hasattr(fn, "step_seconds"):
                        fn.step_seconds = step_seconds

    act = activity.current_activity()
    if act.enabled and write_block is not None:
        # rows-emitted accounting at the FINAL sink (per block, never
        # per row): what the client actually received, after every pipe
        inner_sink = write_block

        def write_block(br):
            act.add("rows_emitted", br.nrows)
            inner_sink(br)

    head = build_processor_chain(q.pipes, write_block or (lambda br: None))
    if act.enabled:
        head = _CancelAwareHead(head, act)
    from ..logsql.pipes import compute_needed_fields
    needed = compute_needed_fields(q.pipes)

    # device stats partials: `<filter> | stats [by (_time:step)] ...` runs
    # as one fused dispatch per part after the filter bitmap, merging
    # per-bucket partials straight into the stats processor
    # (tpu/stats_device.py; reference pipe_stats.go:354-377)
    stats_spec = None
    if runner is not None and hasattr(runner, "run_part_stats"):
        from ..tpu.stats_device import device_stats_spec
        stats_spec = device_stats_spec(q)

    # device sort-topk prefilter: `<filter> | sort by (f) limit N` keeps
    # only rows at-or-above each part's k-th best key (tpu/sort_device.py)
    sort_spec = None
    if stats_spec is None and runner is not None and \
            hasattr(runner, "run_part_topk"):
        from ..tpu.sort_device import device_sort_spec
        sort_spec = device_sort_spec(q)

    # per-part result cache (engine/standing/resultcache.py): a
    # repeated query's sealed parts replay their cached stats partials
    # / filter bitmaps instead of re-dispatching — only the unsealed
    # head recomputes.  for_query returns None when caching can't
    # apply (VL_RESULT_CACHE=0, in(<subquery>) filters).
    from .standing.resultcache import QueryCache
    qcache = QueryCache.for_query(q, tenants, stats_spec, sort_spec,
                                  min_ts, max_ts)

    sfs: list[FilterStream] = []
    _collect_stream_filters(q.filter, sfs)

    # part-level aggregate pruning (filter-index subsystem): AND-path
    # leaves with required word tokens can kill a WHOLE part in O(1)
    # against its Bloofi-style aggregate filter before any per-block
    # work — the per-block bloom kill-path would have zeroed each block
    # anyway, so results are identical (storage/filterbank.py)
    from ..logsql.filters import iter_and_path_token_leaves
    token_leaves = list(iter_and_path_token_leaves(q.filter))

    tenant_set = set(tenants)
    batch = runner is not None and hasattr(runner, "run_part")
    # CPU-path block workers (reference spawns GetConcurrency() workers
    # over a 64-block channel — storage_search.go:1035-1067; numpy/zstd
    # release the GIL, so threads overlap real work).  One pool is SHARED
    # across partitions so total workers stay bounded.
    nworkers = 1 if batch else q.get_concurrency()
    pool = None
    if nworkers > 1:
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(max_workers=nworkers)

    def scan_partition(pt, sink_head):
        with tracing.current_span().span(
                "partition", day=getattr(pt, "day", None)) as psp:
            ctx = SearchContext(partition=pt, tenants=tenants)
            allowed_sids = None
            if sfs:
                allowed_sids = set.intersection(
                    *(f.resolve(pt, tenants) for f in sfs))
                if not allowed_sids:
                    psp.set("pruned_by_stream_filter", True)
                    return
            _scan_parts(pt, q, sink_head, runner, batch, tenant_set,
                        allowed_sids, min_ts, max_ts, ctx, needed,
                        deadline, pool, stats_spec, sort_spec,
                        token_leaves, qcache)

    try:
        pts = storage.select_partitions(min_ts, max_ts)
        if batch and _cross_partition_enabled():
            # device path: ONE dispatch window across every selected
            # partition (tpu/pipeline.scan_device_stream) — parts from
            # partition N+1 submit while partition N harvests, packs
            # may span the day boundary, and prefetch depth survives
            # it.  The window IS the parallelism here (dispatches from
            # several partitions overlap on the one device), so the
            # thread-per-partition fan-out below stays host-only.
            # VL_CROSS_PARTITION=0 restores the per-partition drain.
            _scan_partitions_device(
                pts, q, head, runner, tenants, tenant_set, sfs, min_ts,
                max_ts, needed, deadline, stats_spec, sort_spec,
                token_leaves, qcache)
        else:
            # per-day partitions search CONCURRENTLY under a worker cap
            # (reference storage_search.go:1095-1126): a 30-day query
            # is no longer 30x the single-day latency.  The processor
            # chain is not thread-safe, so partition workers funnel
            # through a locked head; within one partition, block order
            # stays deterministic.
            npw = min(len(pts), q.get_concurrency())
            if npw <= 1:
                for pt in pts:
                    scan_partition(pt, head)
            else:
                _scan_partitions_parallel(pts, scan_partition, head,
                                          npw)
    except QueryCancelled:
        pass
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
    head.flush()


class _SyncHead:
    """Thread-safe facade over the processor chain head for concurrent
    partition workers; also turns the cross-worker stop flag into
    is_done() so sibling scans exit at their next check."""

    def __init__(self, head, lock, stop):
        self._head = head
        self._lock = lock
        self._stop = stop

    def write_block(self, br) -> None:
        with self._lock:
            self._head.write_block(br)

    def absorb_partials(self, key, states) -> None:
        with self._lock:
            self._head.absorb_partials(key, states)

    def is_done(self) -> bool:
        if self._stop.is_set():
            return True
        with self._lock:
            return self._head.is_done()


def _scan_partitions_parallel(pts, scan_partition, head, npw) -> None:
    import threading as _threading
    from concurrent.futures import ThreadPoolExecutor

    lock = _threading.Lock()
    stop = _threading.Event()
    sync_head = _SyncHead(head, lock, stop)
    errors: list = []
    # contextvars don't cross thread spawns: re-enter the caller's span
    # AND activity record in each partition worker so their "partition"
    # spans nest under it and progress counters land on the registry
    parent_span = tracing.current_span()
    parent_act = activity.current_activity()

    def run_one(pt):
        if stop.is_set():
            return
        try:
            with tracing.use_span(parent_span), \
                    activity.use_activity(parent_act):
                scan_partition(pt, sync_head)
        except QueryCancelled:
            stop.set()
        # vlint: allow-broad-except(fan-out error channel, re-raised)
        except Exception as e:
            errors.append(e)
            stop.set()

    with ThreadPoolExecutor(max_workers=npw) as ex:
        list(ex.map(run_one, pts))
    if errors:
        raise errors[0]


def _cross_partition_enabled() -> bool:
    from ..tpu.pipeline import cross_partition_enabled
    return cross_partition_enabled()


def _make_cand_fn(tenant_set, allowed_sids, min_ts, max_ts):
    """Header-only candidate selection closure (shared by the serial
    walk, the cross-partition device stream and the prefetcher);
    candidate_blocks skips whole header groups outside the query's
    time range without decoding them (v2 metaindex)."""
    def cand_block_idxs(part) -> list:
        out = []
        for bi in part.candidate_blocks(min_ts, max_ts):
            sid = part.block_stream_id(bi)
            if sid.tenant not in tenant_set:
                continue
            if allowed_sids is not None and sid not in allowed_sids:
                continue
            out.append(bi)
        return out
    return cand_block_idxs


def _scan_partitions_device(pts, q, head, runner, tenants, tenant_set,
                            sfs, min_ts, max_ts, needed, deadline,
                            stats_spec, sort_spec,
                            token_leaves, qcache=None) -> None:
    """The cross-partition device path: feed every selected partition's
    parts through ONE async dispatch window (tpu/pipeline.py).

    Partition setup stays lazy AND attributed: each partition resolves
    its stream filters and snapshots its parts only when the window's
    planning pull reaches it, under a short-lived per-partition span
    (day, part count, stream-filter prunes — the same attribution the
    per-partition walk recorded); an early exit (limit, deadline,
    cancel) therefore stops the partition walk exactly where the old
    loop would have."""
    from ..tpu.pipeline import scan_device_stream
    qsp = tracing.current_span()
    act = activity.current_activity()

    def part_stream():
        for pt in pts:
            parts = []
            cand_fn = None
            ctx = None
            # the span covers partition SETUP only (it must not stay
            # open across planning pulls — spans are ambient via a
            # contextvar, and a generator holding one open would leak
            # it into the window driver's own spans between pulls)
            with qsp.span("partition", day=getattr(pt, "day",
                                                   None)) as psp:
                ctx = SearchContext(partition=pt, tenants=tenants)
                allowed_sids = None
                if sfs:
                    allowed_sids = set.intersection(
                        *(f.resolve(pt, tenants) for f in sfs))
                    if not allowed_sids:
                        psp.set("pruned_by_stream_filter", True)
                if allowed_sids is None or allowed_sids:
                    parts = [p for p in pt.ddb.snapshot_parts()
                             if p.num_rows and p.min_ts <= max_ts
                             and p.max_ts >= min_ts]
                    psp.set("parts", len(parts))
                    act.add("parts_total", len(parts))
                    cand_fn = _make_cand_fn(tenant_set, allowed_sids,
                                            min_ts, max_ts)
            for part in parts:
                yield part, cand_fn, ctx

    scan_device_stream(part_stream(), q, head, runner, needed, deadline,
                       stats_spec, sort_spec, token_leaves,
                       qcache=qcache)


def _eval_block_cpu(q, bs):
    bm = new_bitmap(bs.nrows)
    q.filter.apply_to_block(bs, bm)
    return bm


def _absorb_stats_partials(head, q, spec, partials) -> None:
    """Fold device per-bucket partials into the stats processor.

    key_parts elements: ("t", bucket_ns) -> RFC3339 (identical to the
    host bucketing), ("v", value) -> the group value string."""
    from ..tpu.stats_device import build_partial_states
    from .block_result import format_rfc3339
    ps = q.pipes[0]
    for key_parts, cnt, field_stats, uniq_vals, quant_vals in partials:
        key = tuple(format_rfc3339(v) if kind == "t" else v
                    for kind, v in key_parts)
        states = build_partial_states(spec, ps.funcs, key, cnt,
                                      field_stats, uniq_vals, quant_vals)
        head.absorb_partials(key, states)


def _scan_parts(pt, q, head, runner, batch, tenant_set, allowed_sids,
                min_ts, max_ts, ctx, needed, deadline, pool,
                stats_spec=None, sort_spec=None,
                token_leaves=None, qcache=None) -> None:
    from ..storage.filterbank import (maplet_prune_candidates,
                                      part_aggregate_prunes)
    parts = [p for p in pt.ddb.snapshot_parts()
             if p.num_rows and p.min_ts <= max_ts and p.max_ts >= min_ts]
    cand_block_idxs = _make_cand_fn(tenant_set, allowed_sids, min_ts,
                                    max_ts)

    if batch:
        # async device pipeline: dispatches for up to VL_INFLIGHT units
        # stay outstanding, small parts pack into super-dispatches, and
        # results harvest in submission order — block order and stats
        # absorb granularity are identical to this serial walk
        # (tpu/pipeline.py)
        from ..tpu.pipeline import scan_parts_device
        scan_parts_device(parts, q, head, runner, cand_block_idxs, ctx,
                          needed, deadline, stats_spec, sort_spec,
                          token_leaves, qcache)
        return

    sp = tracing.current_span()
    sp.set("parts", len(parts))
    act = activity.current_activity()
    act.add("parts_total", len(parts))
    act.set_phase("scan")
    for part in parts:
        if deadline is not None and time.monotonic() > deadline:
            raise QueryTimeoutError(
                "query exceeded -search.maxQueryDuration")
        part_bis = cand_block_idxs(part)
        sp.add("blocks_candidate", len(part_bis))
        if token_leaves and part_bis:
            # part-level aggregate kill (filter-index subsystem): an
            # AND-path leaf's required token absent from EVERY block
            # skips the whole part — identical results, the per-block
            # kill-path would have zeroed each block anyway.  A COLD
            # aggregate build reads all the part's blooms, so it only
            # pays when the candidate set covers a sizable fraction;
            # narrow queries probe an already-built aggregate for free.
            if part_aggregate_prunes(
                    part, token_leaves,
                    build=len(part_bis) * 4 >= part.num_blocks):
                continue
            # sealed v2 parts: the token→block maplet turns AND-path
            # leaf pruning into one exact lookup — surviving blocks
            # are exactly the per-block kill-path's survivors, found
            # before any block header or bloom word is touched
            part_bis = maplet_prune_candidates(part, token_leaves,
                                               part_bis)
            if not part_bis:
                continue
        activity.note_part_scanned(act, part, part_bis)
        if qcache is not None and qcache.kind == "bms":
            # sealed-part replay: the cached bitmaps feed the chain in
            # the exact block order the walk below would produce
            e = qcache.probe(part, part_bis)
            if e is not None:
                cached_bms = qcache.entry_bms(e)
                for bi in part_bis:
                    if head.is_done():
                        raise QueryCancelled()
                    bm = cached_bms[bi]
                    if not bm.any():
                        continue
                    bs = BlockSearch(part, bi)
                    bs.ctx = ctx
                    br = BlockResult.from_block_search(bs, bm, needed)
                    sp.add("blocks_out")
                    sp.add("rows_out", br.nrows)
                    head.write_block(br)
                continue
        collected: dict[int, np.ndarray] = {}
        cand: dict[int, BlockSearch] = {}
        for bi in part_bis:
            if head.is_done():
                raise QueryCancelled()
            bs = BlockSearch(part, bi)
            bs.ctx = ctx
            if pool is not None:
                cand[bi] = bs
                continue
            if runner is not None:
                bm = runner.apply_filter(q.filter, bs)
            else:
                bm = new_bitmap(bs.nrows)
                q.filter.apply_to_block(bs, bm)
            collected[bi] = bm
            if not bm.any():
                continue
            br = BlockResult.from_block_search(bs, bm, needed)
            sp.add("blocks_out")
            sp.add("rows_out", br.nrows)
            head.write_block(br)
        if not cand:
            if qcache is not None:
                qcache.store_bms(part, part_bis, collected)
            continue
        if head.is_done():
            raise QueryCancelled()
        # CPU worker pool: filters evaluate in parallel, results
        # are written downstream in deterministic block order
        order = list(cand)
        results = pool.map(lambda bi: _eval_block_cpu(q, cand[bi]),
                           order)
        bms = dict(zip(order, results))
        for bi, bs in cand.items():
            if head.is_done():
                raise QueryCancelled()
            bm = bms[bi]
            if not bm.any():
                continue
            br = BlockResult.from_block_search(bs, bm, needed)
            sp.add("blocks_out")
            sp.add("rows_out", br.nrows)
            head.write_block(br)
        if qcache is not None:
            collected.update(bms)
            qcache.store_bms(part, part_bis, collected)


def run_query_collect(storage, tenants, q: Query | str,
                      timestamp: int | None = None, runner=None,
                      deadline: float | None = None) -> list[dict]:
    """Execute and collect result rows as dicts (test/API convenience).

    Registers its own activity record when none is ambient (the
    engine-level entry point CLI tools and benches drive directly) so
    every query execution shows up in /select/logsql/active_queries;
    the HTTP handlers register endpoint-specific records first, which
    this inherits instead of double-registering."""
    rows: list[dict] = []

    def sink(br: BlockResult):
        rows.extend(br.rows())

    with _collect_ctx(q, tenants):
        run_query(storage, tenants, q, write_block=sink,
                  timestamp=timestamp, runner=runner, deadline=deadline)
    return rows


def _collect_ctx(q, tenants):
    """Activity registration shared by the collect entry points:
    inherit an ambient record (HTTP handlers register endpoint-specific
    ones first) or self-register."""
    if activity.current_activity().enabled:
        return contextlib.nullcontext()
    # vlint: allow-accounting-discipline(entered by the caller's with)
    return activity.track("run_query_collect",
                          q if isinstance(q, str) else q.to_string(),
                          tenants)


def run_query_collect_columns(storage, tenants, q: Query | str,
                              timestamp: int | None = None, runner=None,
                              deadline: float | None = None
                              ) -> tuple[dict, int]:
    """Columnar twin of run_query_collect: (cols, nrows) where cols is
    an insertion-ordered {name: [str, ...]} with every list nrows long
    (values absent in a block read "").

    Consumers that aggregate result rows (hits/facets/stats endpoints,
    the storage-backed aux pipes) ride this instead of rows() so the
    local and cluster paths share one columnar contract — per-column
    bulk lists, no per-row dict materialization."""
    blocks: list = []            # (names, {name: list}, nrows)
    order: dict[str, None] = {}

    def sink(br: BlockResult):
        names = br.column_names()
        blocks.append((names, {n: br.column(n) for n in names},
                       br.nrows))
        for n in names:
            order.setdefault(n, None)

    with _collect_ctx(q, tenants):
        run_query(storage, tenants, q, write_block=sink,
                  timestamp=timestamp, runner=runner, deadline=deadline)
    total = sum(b[2] for b in blocks)
    cols: dict[str, list] = {n: [] for n in order}
    for _names, bc, n in blocks:
        for name, out in cols.items():
            vals = bc.get(name)
            out.extend(vals if vals is not None else [""] * n)
    return cols, total


# ---- field/value introspection (vlselect support) ----

def get_field_names(storage, tenants, q: Query | str,
                    timestamp: int | None = None) -> list[dict]:
    """Distinct field names with hit counts (reference GetFieldNames)."""
    if isinstance(q, str):
        q = parse_query(q, timestamp)
    hits: dict[str, int] = {}

    def sink(br: BlockResult):
        for n in br.column_names():
            cnt = sum(1 for v in br.column(n) if v != "")
            if n in ("_time", "_stream", "_stream_id"):
                cnt = br.nrows
            if cnt:
                hits[n] = hits.get(n, 0) + cnt
    run_query(storage, tenants, q, write_block=sink, timestamp=timestamp)
    # vlint: allow-per-row-emit(introspection OUTPUT: one dict per distinct name)
    return [{"value": k, "hits": str(hits[k])} for k in sorted(hits)]


def get_field_values(storage, tenants, q: Query | str, field: str,
                     limit: int = 0, timestamp: int | None = None
                     ) -> list[dict]:
    """Distinct values of a field with hit counts (reference GetFieldValues)."""
    if isinstance(q, str):
        q = parse_query(q, timestamp)
    hits: dict[str, int] = {}

    def sink(br: BlockResult):
        for v in br.column(field):
            if v != "":
                hits[v] = hits.get(v, 0) + 1
    run_query(storage, tenants, q, write_block=sink, timestamp=timestamp)
    # vlint: allow-per-row-emit(introspection OUTPUT: one dict per distinct value)
    out = [{"value": k, "hits": str(hits[k])} for k in sorted(hits)]
    if limit and len(out) > limit:
        out = out[:limit]
    return out


def get_streams(storage, tenants, q: Query | str, limit: int = 0,
                timestamp: int | None = None) -> list[dict]:
    return get_field_values(storage, tenants, q, "_stream", limit, timestamp)


def get_stream_ids(storage, tenants, q: Query | str, limit: int = 0,
                   timestamp: int | None = None) -> list[dict]:
    return get_field_values(storage, tenants, q, "_stream_id", limit,
                            timestamp)
