"""Per-part query result cache: repeated queries recompute only the
unsealed head.

Dashboard/alert traffic is dominated by the SAME query re-run over a
sliding window.  Parts are immutable and uid'd, and the device stats
path already produces per-part partials (the segment axis), so the
per-part share of a repeated query's answer is a pure function of
(query fingerprint, part uid) — cacheable forever, staleness-proof by
construction: a merge mints fresh uids and the old entries die with
their parts' GC finalizers, exactly like the bloom bank
(storage/filterbank.py).

What is cached, per (fingerprint, part uid):

- ``stats`` entries — the raw per-part partial tuples a fused stats
  dispatch harvested, BEFORE build_partial_states: replaying them
  through the same absorb path merges to the bit-identical uncached
  answer (float accumulation order is preserved — partials re-merge in
  the same part order);
- ``bms`` entries — per-block filter bitmaps (np.packbits'd), for rows
  queries and sort-topk prefilters.  Topk bitmaps are keyed by the
  (field, desc, k) shape: they are a per-part superset of any smaller
  re-ask of the same shape only for the SAME k, so the key carries it.

Safety rules (enforced at both store and probe):

- the query's top-level AND-path time range must fully cover the part
  (the stripped time filter is then a row-level no-op on it) — the
  range itself stays OUT of the fingerprint, so every 15s-refresh
  sliding window hits the same keys;
- the candidate block list must match exactly (tenants and stream
  filters are in the fingerprint; the bis check is belt-and-braces);
- queries with in(<subquery>) filters never cache (materialized values
  depend on mutable storage contents, not the query text).

Budget: ``VL_RESULT_CACHE_MAX_BYTES``, accounted like the bloom bank —
per-part charge lists released by a ``weakref.finalize`` at part GC,
LRU eviction past the budget, and a ``cache_check_balanced()`` twin for
the vlsan end-of-test sweep (cache bytes == sum of live charges >= 0).
``VL_RESULT_CACHE=0`` is the kill switch.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ... import config
from ...logsql.filters import FilterAnd, FilterTime
from ...logsql.parser import MAX_TS, MIN_TS


def cache_enabled() -> bool:
    return config.env_flag("VL_RESULT_CACHE")


def _cache_max_bytes() -> int:
    return config.env_int("VL_RESULT_CACHE_MAX_BYTES")


# ---------------- the byte-budgeted store ----------------

_cache_mu = threading.Lock()
_cache_bytes = 0
# (fingerprint, part uid) -> _Entry, LRU order (move_to_end on hit)
_entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
# part uid -> set of live keys, so a part's GC finalizer can drop its
# entries without a full scan
_part_index: dict[int, set] = {}
# every live Part whose ._rc_charged list was handed to a _rc_release
# weakref.finalize — the vlsan sweep proves _cache_bytes == sum of live
# charges (>= 0) after every test (tools/vlint/vlsan.py)
_cache_owners: "weakref.WeakSet" = weakref.WeakSet()
_counts = {"hits": 0, "misses": 0, "evictions": 0, "stores": 0}


@dataclass
class _Entry:
    kind: str                     # "stats" | "bms"
    bis: tuple                    # candidate block idxs the value covers
    value: object                 # stats: list of raw partial tuples;
    #                               bms: {bi: (nrows, packed uint8)}
    nbytes: int
    charges: list                 # the owning part's live charge list


def _sizeof(v) -> int:
    """Recursive byte estimate for budget accounting (exact for the
    ndarray payloads that dominate; fixed overheads elsewhere)."""
    if isinstance(v, np.ndarray):
        return int(v.nbytes) + 64
    if isinstance(v, (bytes, bytearray, str)):
        return len(v) + 48
    if isinstance(v, (list, tuple, set, frozenset)):
        return 56 + sum(_sizeof(x) for x in v)
    if isinstance(v, dict):
        return 64 + sum(_sizeof(k) + _sizeof(x) for k, x in v.items())
    return 32


def _rc_try_charge(part, n: int) -> tuple[bool, list]:
    """Reserve n bytes against the budget for one of `part`'s entries,
    evicting LRU entries of OTHER keys if needed.  Returns
    (ok, evicted_keys) — the caller emits the evict events OUTSIDE the
    lock (journal emit must never run under _cache_mu)."""
    global _cache_bytes
    evicted = []
    with _cache_mu:
        maxb = _cache_max_bytes()
        if n > maxb:
            return False, evicted
        while _cache_bytes + n > maxb and _entries:
            key, e = _entries.popitem(last=False)
            _part_index.get(key[1], set()).discard(key)
            e.charges.remove(e.nbytes)
            _cache_bytes -= e.nbytes
            _counts["evictions"] += 1
            evicted.append(key)
        if _cache_bytes + n > maxb:
            return False, evicted
        charges = getattr(part, "_rc_charged", None)
        if charges is None:
            charges = part._rc_charged = []
            weakref.finalize(part, _rc_release, part.uid, charges)
            _cache_owners.add(part)
        charges.append(n)
        _cache_bytes += n
        return True, evicted


def _rc_release(uid, charges: list) -> None:
    """weakref.finalize callback: a collected part returns its entries'
    bytes to the budget and drops its keys (charges is the part's live
    charge list — entries evicted earlier already removed their
    share)."""
    global _cache_bytes
    with _cache_mu:
        for key in _part_index.pop(uid, ()):
            _entries.pop(key, None)
        _cache_bytes -= sum(charges)
        charges.clear()


def cache_check_balanced() -> tuple[bool, str]:
    """Budget-accounting invariant for the vlsan sweep: the byte total
    equals both the sum of every live owner's charges and the sum of
    live entry sizes, and never goes negative.  Callers retry after
    gc.collect() — a part finalizer may not have run yet."""
    with _cache_mu:
        used = _cache_bytes
        entry_bytes = sum(e.nbytes for e in _entries.values())
    live = sum(sum(o._rc_charged) for o in list(_cache_owners))
    ok = used == live == entry_bytes and used >= 0
    return ok, (f"cache_bytes={used} sum(live charges)={live} "
                f"sum(entry nbytes)={entry_bytes}")


def cache_stats() -> dict:
    with _cache_mu:
        return {"used_bytes": _cache_bytes,
                "max_bytes": _cache_max_bytes(),
                "entries": len(_entries), **_counts}


def metrics_samples() -> list[tuple[str, dict, float]]:
    """(base, labels, value) samples for server/app.py Metrics.render."""
    s = cache_stats()
    return [
        ("vl_result_cache_hits_total", {}, s["hits"]),
        ("vl_result_cache_misses_total", {}, s["misses"]),
        ("vl_result_cache_evictions_total", {}, s["evictions"]),
        ("vl_result_cache_stores_total", {}, s["stores"]),
        ("vl_result_cache_bytes", {}, s["used_bytes"]),
        ("vl_result_cache_max_bytes", {}, s["max_bytes"]),
        ("vl_result_cache_entries", {}, s["entries"]),
    ]


def reset_for_tests() -> None:
    """Drop every entry and zero the counters (test isolation only —
    charges release through the normal accounting so the balance
    invariant holds across the reset)."""
    global _cache_bytes
    with _cache_mu:
        for key, e in _entries.items():
            e.charges.remove(e.nbytes)
            _cache_bytes -= e.nbytes
        _entries.clear()
        _part_index.clear()
        for k in _counts:
            _counts[k] = 0


def _emit_evictions(evicted: list) -> None:
    if not evicted:
        return
    from ...obs import events
    events.emit("result_cache_evict", entries=len(evicted),
                fingerprint=evicted[0][0])


# ---------------- fingerprints ----------------

def _has_subquery(f) -> bool:
    from ..searcher import _iter_subquery_filters
    return any(True for _ in _iter_subquery_filters(f))


def _time_free_filter_str(f) -> str:
    """The filter's to_string with top-level AND-path FilterTime nodes
    removed — the sliding-window part of a dashboard query, which the
    full-coverage validity rule makes a per-part no-op.  Nested time
    filters (inside or:/NOT) stay in the string: they narrow rows and
    must key the entry."""
    if isinstance(f, FilterTime):
        return "*"
    if isinstance(f, FilterAnd):
        subs = [s for s in f.filters if not isinstance(s, FilterTime)]
        if not subs:
            return "*"
        if len(subs) == 1:
            return subs[0].to_string()
        return FilterAnd(subs).to_string()
    return f.to_string()


class QueryCache:
    """One query execution's view of the global store: the fingerprint,
    the validity window, and per-query hit/miss accounting.

    ``for_query`` returns None when the cache cannot apply (kill
    switch, subquery filters) — callers then skip every hook.
    """

    def __init__(self, fp_probe: tuple, fp_store: str, kind: str,
                 min_ts: int, max_ts: int):
        self._fp_probe = fp_probe     # fingerprints to try, in order
        self._fp_store = fp_store     # fingerprint new entries key on
        self.kind = kind              # "stats" | "bms"
        self._min_ts = min_ts
        self._max_ts = max_ts
        self.hits = 0
        self.misses = 0
        self.hit_uids: set = set()

    @staticmethod
    def for_query(q, tenants, stats_spec, sort_spec, min_ts, max_ts
                  ) -> "QueryCache | None":
        if not cache_enabled():
            return None
        if _has_subquery(q.filter):
            return None
        from ...obs import activity
        tstr = ",".join(sorted(activity.tenant_str(t) for t in tenants))
        base = hashlib.sha1(
            (_time_free_filter_str(q.filter) + "\x00" + tstr)
            .encode()).hexdigest()
        rows_fp = base + ":rows"
        if stats_spec is not None:
            # the stats subtree keys the partials; NO rows-bitmap
            # fallback — replayed partials preserve the float
            # accumulation order, a bitmap re-scan would not
            fp = base + ":stats:" + q.pipes[0].to_string()
            return QueryCache((fp,), fp, "stats", min_ts, max_ts)
        if sort_spec is not None:
            # a topk prefilter keeps every row at-or-above the part's
            # k-th best key — full rows bitmaps are a valid superset,
            # so probe falls back to them; stores stay under the topk
            # key (the prefiltered bitmaps are NOT general rows answers)
            fp = (base + f":topk:{sort_spec.field}:"
                  f"{int(sort_spec.desc)}:{sort_spec.k}")
            return QueryCache((fp, rows_fp), fp, "bms", min_ts, max_ts)
        return QueryCache((rows_fp,), rows_fp, "bms", min_ts, max_ts)

    # -- validity --

    def _covers(self, part) -> bool:
        """The query's time range fully covers the part (the stripped
        top-level time filter then keeps every row of it)."""
        return ((self._min_ts == MIN_TS or part.min_ts >= self._min_ts)
                and (self._max_ts == MAX_TS
                     or part.max_ts <= self._max_ts))

    def _lookup(self, part, bis):
        if not self._covers(part):
            return None
        bist = tuple(bis)
        with _cache_mu:
            for fp in self._fp_probe:
                e = _entries.get((fp, part.uid))
                if e is not None and e.bis == bist:
                    _entries.move_to_end((fp, part.uid))
                    return e
        return None

    # -- probe (execution) --

    def probe(self, part, bis):
        """The cached entry covering (part, bis), or None.  Counts the
        per-query and global hit/miss totals; ``peek`` is the EXPLAIN
        twin that touches neither."""
        e = self._lookup(part, bis)
        with _cache_mu:
            _counts["hits" if e is not None else "misses"] += 1
        if e is not None:
            self.hits += 1
            self.hit_uids.add(part.uid)
        else:
            self.misses += 1
        return e

    def peek(self, part, bis) -> bool:
        return self._lookup(part, bis) is not None

    # -- hit materialization --

    @staticmethod
    def entry_partials(e) -> list:
        return list(e.value)

    @staticmethod
    def entry_bms(e) -> dict:
        out = {}
        for bi, (nrows, packed) in e.value.items():
            out[bi] = np.unpackbits(packed, count=nrows).view(bool)
        return out

    # -- store (harvest/absorb) --

    def _store(self, part, bis, kind: str, value, evicted_out: list
               ) -> None:
        global _cache_bytes
        if part.uid in self.hit_uids or not self._covers(part):
            return
        if not isinstance(part.uid, int):
            return                    # never cache a PackedPart facade
        key = (self._fp_store, part.uid)
        nbytes = _sizeof(value)
        ok, evicted = _rc_try_charge(part, nbytes)
        evicted_out.extend(evicted)
        if not ok:
            return
        with _cache_mu:
            old = _entries.pop(key, None)
            if old is not None:
                # a concurrent query of the same shape raced us here:
                # keep ours, return the loser's bytes
                old.charges.remove(old.nbytes)
                _cache_bytes -= old.nbytes
            _entries[key] = _Entry(kind, tuple(bis), value, nbytes,
                                   part._rc_charged)
            _part_index.setdefault(part.uid, set()).add(key)
            _counts["stores"] += 1

    def store_member(self, m) -> None:
        """Harvest-side population (tpu/pipeline.py emit): cache a
        fully-materialized member's result when its shape matches the
        query's cache kind."""
        evicted: list = []
        bis = [bi for bi, _bs in m.blocks]
        if self.kind == "stats":
            # only a FULLY partial-handled member replays exactly; a
            # mixed member (some blocks fell back to bitmaps) declines
            if m.handled == set(bis):
                self._store(m.part, bis, "stats", list(m.partials),
                            evicted)
        elif not m.partials and not m.handled and \
                all(bi in m.bms for bi in bis):
            packed = {bi: (int(m.bms[bi].shape[0]),
                           np.packbits(m.bms[bi]))
                      for bi in bis}
            self._store(m.part, bis, "bms", packed, evicted)
        _emit_evictions(evicted)

    def store_bms(self, part, bis, bms: dict) -> None:
        """Serial-walk population (engine/searcher._scan_parts)."""
        if self.kind != "bms" or any(bi not in bms for bi in bis):
            return
        evicted: list = []
        packed = {bi: (int(bms[bi].shape[0]), np.packbits(bms[bi]))
                  for bi in bis}
        self._store(part, bis, "bms", packed, evicted)
        _emit_evictions(evicted)
