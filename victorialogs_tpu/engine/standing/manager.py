"""Standing queries: one resident evaluation per distinct query
fingerprint per node, fanned out to N subscribers.

A dashboard panel refreshed by many clients is the same LogsQL query
re-POSTed over and over.  A standing registration
(``POST /select/logsql/standing_query`` — server/app.py) collapses all
of them to ONE entry keyed by the query's fingerprint: the entry keeps
its latest result resident, subscribes to the journal bus's
``storage_flush``/``storage_merge`` events, re-evaluates when storage
actually changed, and pushes the updated result bytes to every
subscriber queue (the /tail streaming machinery drains them to the
clients).  Re-evaluation rides the per-part result cache
(resultcache.py), so each push recomputes only the parts the flush or
merge minted — price-after-cache, and the admission controller prices
exactly that residual work (``admission.admit`` wraps every re-eval).

Lifecycle discipline (the vlsan/balance-checked invariants):

- ``attach_subscriber``/``detach_subscriber`` bracket every consumer —
  the LAST detach drops the entry (and an explicit ``unregister``
  pushes a ``None`` sentinel so attached streams end);
- the bus subscription exists while ANY entry does (first register
  subscribes, last drop unsubscribes — both in this module, the PR 8
  ``is``-vs-``==`` class);
- ``standing_query_{registered,unregistered,reeval}`` journal events
  carry the entry's tenant, so standing evaluations of journal-only
  data are suppressed by the PR 8 recursion guard and cannot
  self-heartbeat.

``VL_STANDING=0`` kills registration; ``VL_STANDING_MAX`` caps entries
per node; ``VL_STANDING_DEBOUNCE_MS`` coalesces flush bursts into one
re-evaluation.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import weakref

from ... import config
from ...obs import activity, events

# every live registry, for /metrics gauges and the vlsan sweep
_registries: "weakref.WeakSet" = weakref.WeakSet()
_counts_mu = threading.Lock()
_counts = {"reevals": 0, "pushes_dropped": 0}

# per-subscriber queue depth: a stalled client drops ITS oldest
# payloads (counted) without blocking the evaluation or its siblings
_SUB_QUEUE_DEPTH = 8


def standing_enabled() -> bool:
    return config.env_flag("VL_STANDING")


def standing_max() -> int:
    return config.env_int("VL_STANDING_MAX")


def _bump(key: str, n: int = 1) -> None:
    with _counts_mu:
        _counts[key] += n


def standing_fingerprint(q, tenants) -> str:
    tstr = ",".join(sorted(activity.tenant_str(t) for t in tenants))
    return hashlib.sha1(
        (q.to_string() + "\x00" + tstr).encode()).hexdigest()


class StandingLimit(Exception):
    """Registration refused: VL_STANDING_MAX reached (HTTP 429) or
    VL_STANDING=0 (HTTP 503)."""


class _Standing:
    """One registered query fingerprint and its subscriber fan-out."""

    def __init__(self, fp: str, q, tenants: tuple, parent_qid: str):
        self.fp = fp
        self.q = q
        self.tenants = tenants
        self.parent_qid = parent_qid
        self.subs: list[queue.Queue] = []
        self.last_payload: bytes | None = None
        self.reevals = 0
        self.dirty = False

    def tenant(self) -> str:
        return activity.tenant_str(self.tenants[0])


class StandingRegistry:
    """Per-server standing-query registry (server/app.py owns one)."""

    def __init__(self, storage, runner=None, admission=None):
        self._storage = storage
        self._runner = runner
        self._admission = admission
        self._mu = threading.Lock()
        self._entries: dict[str, _Standing] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self._subscribed = False
        _registries.add(self)

    # -- registration --

    def register(self, q, tenants, parent_qid: str = "") -> str:
        """Register (or join) the standing evaluation for q; returns
        its fingerprint.  The first registration evaluates immediately
        so a joining subscriber is seeded with the current result."""
        if not standing_enabled():
            raise StandingLimit("standing queries disabled "
                                "(VL_STANDING=0)")
        tenants = tuple(tenants)
        fp = standing_fingerprint(q, tenants)
        created = None
        with self._mu:
            e = self._entries.get(fp)
            if e is None:
                if len(self._entries) >= standing_max():
                    raise StandingLimit(
                        f"standing query limit reached "
                        f"(VL_STANDING_MAX={standing_max()})")
                e = created = _Standing(fp, q, tenants, parent_qid)
                self._entries[fp] = e
                if not self._subscribed:
                    events.subscribe(self._on_event)
                    self._subscribed = True
                self._ensure_worker()
        if created is not None:
            events.emit("standing_query_registered",
                        tenant=created.tenant(), fingerprint=fp,
                        query=q.to_string(), parent_qid=parent_qid)
            try:
                self._reeval(created)
            except BaseException:
                # a failed seed evaluation (admission shed, bad query
                # against the live schema) must not leave a
                # subscriber-less entry resident forever
                self.unregister(fp)
                raise
        return fp

    def unregister(self, fp: str) -> bool:
        """Explicit teardown: attached subscriber streams receive the
        end-of-stream sentinel and the entry drops immediately."""
        with self._mu:
            e = self._entries.pop(fp, None)
            if e is not None:
                subs = list(e.subs)
                e.subs.clear()
                self._maybe_unsubscribe_locked()
        if e is None:
            return False
        for sub in subs:
            self._push_one(sub, None)
        events.emit("standing_query_unregistered", tenant=e.tenant(),
                    fingerprint=fp, reason="unregister")
        return True

    # -- subscribers --

    def attach_subscriber(self, fp: str) -> queue.Queue:
        """One consumer's delta queue, seeded with the latest result so
        a joining dashboard paints without waiting for the next flush.
        Always balanced by detach_subscriber (vlint balance pair)."""
        with self._mu:
            e = self._entries.get(fp)
            if e is None:
                raise KeyError(fp)
            sub: queue.Queue = queue.Queue(_SUB_QUEUE_DEPTH)
            e.subs.append(sub)
            if e.last_payload is not None:
                sub.put_nowait(e.last_payload)
        return sub

    def detach_subscriber(self, fp: str, sub) -> None:
        """The LAST detach drops the whole entry — a standing query
        nobody is watching must not keep re-evaluating."""
        dropped = None
        with self._mu:
            e = self._entries.get(fp)
            if e is None:
                return
            if sub in e.subs:
                e.subs.remove(sub)
            if not e.subs:
                dropped = self._entries.pop(fp)
                self._maybe_unsubscribe_locked()
        if dropped is not None:
            events.emit("standing_query_unregistered",
                        tenant=dropped.tenant(), fingerprint=fp,
                        reason="last_subscriber_detached")

    def _maybe_unsubscribe_locked(self) -> None:
        if self._subscribed and not self._entries:
            events.unsubscribe(self._on_event)
            self._subscribed = False

    # -- the journal-bus trigger --

    def _on_event(self, ts_ns, event, fields) -> None:
        """Runs on the EMITTER's thread (storage flush/merge): mark and
        wake, never evaluate here."""
        if event not in ("storage_flush", "storage_merge"):
            return
        with self._mu:
            for e in self._entries.values():
                e.dirty = True
        self._wake.set()

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="vl-standing", daemon=True)
            self._worker.start()

    def _run(self) -> None:
        debounce_s = config.env_int("VL_STANDING_DEBOUNCE_MS") / 1e3
        while not self._stop.is_set():
            if not self._wake.wait(0.5):
                continue
            # coalesce a flush burst (a merge right after its flushes)
            # into ONE re-evaluation per entry
            if self._stop.wait(debounce_s):
                break
            self._wake.clear()
            with self._mu:
                todo = [e for e in self._entries.values() if e.dirty]
                for e in todo:
                    e.dirty = False
            for e in todo:
                if self._stop.is_set():
                    break
                try:
                    self._reeval(e)
                # vlint: allow-broad-except(one broken standing entry must not kill the shared worker)
                except Exception:
                    with self._mu:
                        e.dirty = True

    # -- evaluation --

    def _reeval(self, e: _Standing) -> None:
        """ONE full evaluation of the standing query; sealed parts hit
        the result cache so only flush/merge-minted parts re-dispatch.
        The push is the delta: subscribers receive the new result bytes
        only when they differ from the previous push."""
        from ..searcher import run_query
        from ..emit import ndjson_block
        chunks: list[bytes] = []

        def sink(br):
            chunks.append(ndjson_block(br))

        def run():
            with activity.track("/select/logsql/standing_query",
                                e.q.to_string(), e.tenants,
                                parent_qid=e.parent_qid):
                run_query(self._storage, list(e.tenants), e.q.clone(),
                          write_block=sink, runner=self._runner)

        adm = self._admission
        if adm is not None:
            # standing re-evaluations are PRICED tenant workload: the
            # admission pool sees the post-cache residual scan exactly
            # like an interactive query (AdmissionShed re-marks dirty
            # via the worker's retry path)
            with adm.admit(tenant=e.tenant(),
                           endpoint="/select/logsql/standing_query"):
                run()
        else:
            run()
        payload = b"".join(chunks)
        changed = payload != e.last_payload
        dropped = 0
        with self._mu:
            e.last_payload = payload
            e.reevals += 1
            subs = list(e.subs) if changed else []
        _bump("reevals")
        for sub in subs:
            dropped += self._push_one(sub, payload)
        if dropped:
            _bump("pushes_dropped", dropped)
        events.emit("standing_query_reeval", tenant=e.tenant(),
                    fingerprint=e.fp, bytes=len(payload),
                    changed=changed, subscribers=len(subs))

    @staticmethod
    def _push_one(sub: queue.Queue, payload) -> int:
        """Enqueue-or-drop-oldest: a stalled subscriber loses ITS
        backlog (returned as the drop count), never blocks the
        evaluation."""
        dropped = 0
        while True:
            try:
                sub.put_nowait(payload)
                return dropped
            except queue.Full:
                try:
                    sub.get_nowait()
                    dropped += 1
                except queue.Empty:
                    continue

    # -- introspection / teardown --

    def snapshot(self) -> list[dict]:
        with self._mu:
            # vlint: allow-per-row-emit(introspection metadata, bounded by VL_STANDING_MAX)
            return [{
                "fingerprint": e.fp,
                "query": e.q.to_string(),
                "tenant": e.tenant(),
                "parent_qid": e.parent_qid,
                "subscribers": len(e.subs),
                "reevals": e.reevals,
            } for e in self._entries.values()]

    def entry_count(self) -> int:
        with self._mu:
            return len(self._entries)

    def reeval_now(self, fp: str) -> bool:
        """Synchronous re-evaluation (bench/test determinism)."""
        with self._mu:
            e = self._entries.get(fp)
        if e is None:
            return False
        self._reeval(e)
        return True

    def close(self) -> None:
        for fp in [e["fingerprint"] for e in self.snapshot()]:
            self.unregister(fp)
        self._stop.set()
        self._wake.set()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout=5)
        with self._mu:
            self._maybe_unsubscribe_locked()


def standing_snapshot() -> list[dict]:
    """Every live registry's entries (the /metrics and vlsan view)."""
    out = []
    for r in list(_registries):
        out.extend(r.snapshot())
    return out


def standing_check_drained(baseline: int = 0) -> tuple[bool, str]:
    """vlsan end-of-test sweep: the standing registry must be back to
    its per-test baseline — a leaked entry keeps a resident evaluation
    (and its bus subscription) alive forever."""
    entries = standing_snapshot()
    ok = len(entries) <= baseline
    return ok, (f"standing entries={len(entries)} baseline={baseline} "
                f"({[e['fingerprint'][:8] for e in entries]})")


def metrics_samples() -> list[tuple[str, dict, float]]:
    entries = standing_snapshot()
    with _counts_mu:
        c = dict(_counts)
    return [
        ("vl_standing_queries", {}, len(entries)),
        ("vl_standing_subscribers", {},
         sum(e["subscribers"] for e in entries)),
        ("vl_standing_reevals_total", {}, c["reevals"]),
        ("vl_standing_pushes_dropped_total", {}, c["pushes_dropped"]),
    ]
