"""Standing-query subsystem: per-part result cache + incremental
dashboard evaluation.

Two layers over the immutable-part storage model:

- ``resultcache`` — a byte-budgeted cache of per-part query results
  (stats partials / filter bitmaps) keyed by (query fingerprint, part
  uid).  Parts are immutable, so a key can never go stale; a repeated
  dashboard query recomputes only the unsealed head parts.

- ``manager`` — standing-query registrations: one resident evaluation
  per distinct query fingerprint per node, re-run on the journal bus's
  storage_flush/storage_merge events and fanned out to N subscribers
  over the /tail streaming machinery.
"""

from .resultcache import (QueryCache, cache_check_balanced, cache_stats,
                          metrics_samples, reset_for_tests)
from .manager import StandingRegistry, standing_check_drained
from .manager import metrics_samples as standing_metrics_samples

__all__ = [
    "QueryCache", "cache_check_balanced", "cache_stats",
    "metrics_samples", "reset_for_tests", "StandingRegistry",
    "standing_check_drained", "standing_metrics_samples",
]
