"""Network fault injection: the chaos counterpart of ``inject_fault``.

Two cooperating mechanisms drive the cluster-robustness test matrix
(server/netrobust.py is the consumer):

- **in-process client-side faults** — ``inject_net_fault(mode, nth)``
  arms a deterministic one-shot failure of a chosen upcoming cluster
  HTTP attempt, and ``VL_FAULT_NET="<mode>:<prob>"`` fails each attempt
  with probability ``prob``.  Only the modes a CLIENT can simulate
  without a wire exist here: ``refuse`` (connection refused before any
  bytes move) and ``5xx`` (the node answered 503).  Every injection
  emits a ``fault_injected`` journal event so a chaos run's synthetic
  failures correlate with the retries/breaker transitions they caused;

- :class:`FaultProxy` — a real in-process TCP proxy for the wire-level
  modes no client-side hook can fake: ``hang`` (accept, then silence),
  ``reset`` (RST mid-response-stream), ``trickle`` (bytes dribble out
  slower than any progress), plus ``refuse`` / ``5xx`` / ``pass``.
  Tests and ``make bench-faults`` park it between a frontend and one
  storage node and flip ``set_mode`` to kill/degrade/revive that node
  without touching the node process.

Import discipline: this module must stay importable without the server
package (sched is below server in the layer order), so it raises plain
``OSError`` subclasses / returns mode strings and lets netrobust do the
HTTP-flavored wrapping.
"""

from __future__ import annotations

import socket
import struct
import threading
from .. import config

from ..obs import events

NET_MODES = ("refuse", "5xx")          # client-side injectable
PROXY_MODES = ("pass", "refuse", "5xx", "hang", "reset", "trickle")

_mu = threading.Lock()
_targets: list[tuple[int, str]] = []   # (attempt_no, mode)
_attempt_count = 0


class InjectedNetFault(ConnectionRefusedError):
    """An injected ``refuse`` fault (an OSError, so the policy layer
    classifies it exactly like a real dead node)."""


def inject_net_fault(mode: str = "refuse", nth: int = 0) -> None:
    """Arm a one-shot network fault: the (nth+1)-th cluster HTTP attempt
    from now fails with ``mode`` (deterministic counterpart of
    VL_FAULT_NET, mirroring scheduler.inject_fault)."""
    if mode not in NET_MODES:
        raise ValueError(f"unknown net fault mode {mode!r} "
                         f"(client-side modes: {NET_MODES})")
    with _mu:
        _targets.append((_attempt_count + 1 + max(0, int(nth)), mode))


def clear_net_faults() -> None:
    with _mu:
        _targets.clear()


def maybe_fail_net(url: str) -> str | None:
    """Called by netrobust immediately before each cluster HTTP attempt.
    Returns the injected mode ("refuse" / "5xx") or None.  AFTER the
    breaker admitted the attempt, so chaos runs exercise the real
    failure-accounting path."""
    global _attempt_count
    with _mu:
        _attempt_count += 1
        n = _attempt_count
        hit = next((t for t in _targets if t[0] == n), None)
        if hit is not None:
            _targets.remove(hit)
    mode = hit[1] if hit is not None else None
    source = "inject_net_fault"
    if mode is None:
        spec = config.env("VL_FAULT_NET") or ""
        if spec:
            m, _, p = spec.partition(":")
            try:
                prob = float(p) if p else 1.0
            except ValueError:
                prob = 0.0
            if m in NET_MODES and prob > 0:
                import random
                if prob >= 1.0 or random.random() < prob:
                    mode = m
                    source = "VL_FAULT_NET"
    if mode is not None:
        events.emit("fault_injected", kind="net", mode=mode, url=url,
                    attempt_no=n, source=source)
    return mode


# ---------------- the wire-level fault proxy ----------------

_HTTP_503 = (b"HTTP/1.1 503 Service Unavailable\r\n"
             b"Content-Type: text/plain\r\n"
             b"Content-Length: 23\r\n"
             b"Connection: close\r\n\r\n"
             b"injected fault: 5xx\r\n\r\n")


class FaultProxy:
    """In-process TCP proxy with switchable failure modes (see module
    docstring).  Listens on an OS-assigned localhost port; point a
    frontend's ``-storageNode`` at :attr:`url` and flip ``set_mode`` to
    chaos the hop."""

    def __init__(self, target_host: str, target_port: int,
                 reset_after_bytes: int = 256,
                 trickle_delay_s: float = 0.25):
        self.target = (target_host, int(target_port))
        self.reset_after_bytes = reset_after_bytes
        self.trickle_delay_s = trickle_delay_s
        self._mode = "pass"
        self._mu = threading.Lock()
        self._closed = threading.Event()
        self._conns: list[socket.socket] = []
        self._ls = socket.socket()
        self._ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ls.bind(("127.0.0.1", 0))
        self._ls.listen(32)
        self.port = self._ls.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def mode(self) -> str:
        with self._mu:
            return self._mode

    def set_mode(self, mode: str) -> None:
        if mode not in PROXY_MODES:
            raise ValueError(f"unknown proxy mode {mode!r} "
                             f"(modes: {PROXY_MODES})")
        with self._mu:
            self._mode = mode
            conns, self._conns = self._conns, []
        # changing mode cuts every live relay: a revive ("pass") must
        # not leave a pre-fault hung connection pinning a client
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _track(self, *socks) -> None:
        with self._mu:
            self._conns.extend(socks)

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                client, _addr = self._ls.accept()
            except OSError:
                return
            mode = self.mode
            if mode == "refuse":
                # immediate close: the client sees ECONNRESET/EOF
                # before any HTTP bytes — the dead-node signature
                try:
                    client.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                    client.close()
                except OSError:
                    pass
                continue
            self._track(client)
            threading.Thread(target=self._serve, args=(client, mode),
                             daemon=True).start()

    def _serve(self, client: socket.socket, mode: str) -> None:
        try:
            if mode == "5xx":
                self._read_request(client)
                client.sendall(_HTTP_503)
                client.close()
                return
            if mode == "hang":
                # accept + swallow the request, answer nothing: the
                # straggler-node case the per-read deadline exists for.
                # Clear _read_request's poll timeout: a REAL hang never
                # answers until the mode changes or the proxy closes
                # (set_mode/close close this socket, waking the recv)
                self._read_request(client)
                client.settimeout(None)
                while not self._closed.is_set():
                    if client.recv(65536) == b"":
                        break
                return
            self._relay(client, mode)
        except OSError:
            pass
        finally:
            try:
                client.close()
            except OSError:
                pass

    @staticmethod
    def _read_request(client: socket.socket) -> bytes:
        """Read until the request is plausibly complete (headers + any
        body already in flight); bounded, never exact — the faults only
        need the client to have committed its bytes."""
        client.settimeout(0.5)
        buf = b""
        try:
            while len(buf) < 1 << 20:
                chunk = client.recv(65536)
                if not chunk:
                    break
                buf += chunk
        except socket.timeout:
            pass
        return buf

    def _relay(self, client: socket.socket, mode: str) -> None:
        """pass / reset / trickle: forward to the real node, degrading
        the RESPONSE leg for the degraded modes."""
        up = socket.create_connection(self.target, timeout=10)
        self._track(up)

        def c2s() -> None:
            try:
                while True:
                    data = client.recv(65536)
                    if not data:
                        break
                    up.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    up.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        threading.Thread(target=c2s, daemon=True).start()
        sent = 0
        try:
            while True:
                data = up.recv(65536)
                if not data:
                    break
                if mode == "reset" and \
                        sent + len(data) > self.reset_after_bytes:
                    keep = max(0, self.reset_after_bytes - sent)
                    if keep:
                        client.sendall(data[:keep])
                    # SO_LINGER(1, 0): close() sends RST, not FIN —
                    # the mid-stream connection-reset signature.  The
                    # c2s thread is blocked in recv() on this socket;
                    # its in-flight syscall holds the kernel file ref,
                    # which would DEFER the close (and the RST)
                    # indefinitely — shutdown(SHUT_RD) wakes it with
                    # EOF without putting a FIN on the wire, then the
                    # close fires the RST
                    client.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                    try:
                        client.shutdown(socket.SHUT_RD)
                    except OSError:
                        pass
                    self._closed.wait(0.05)
                    client.close()
                    return
                if mode == "trickle":
                    for i in range(0, len(data), 64):
                        if self._closed.wait(self.trickle_delay_s):
                            return
                        client.sendall(data[i:i + 64])
                    sent += len(data)
                else:
                    client.sendall(data)
                    sent += len(data)
        except OSError:
            pass
        finally:
            try:
                up.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._ls.close()
        except OSError:
            pass
        with self._mu:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
