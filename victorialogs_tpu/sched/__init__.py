"""Overload-safe query scheduling.

Three cooperating pieces turn "fast for one query" into "stays up
under production traffic":

- :mod:`.admission` — per-tenant admission control at the HTTP/cluster
  entry (bounded queue, 429 + Retry-After shedding with machine-
  readable reasons, deadline-aware early rejection);
- :mod:`.scheduler` — the shared device-dispatch scheduler: one global
  in-flight budget, submit slots leased per dispatch unit with
  weighted fair queuing across active queries (tpu/pipeline.py);
- fault injection (``inject_fault`` / ``VL_FAULT_SUBMIT``) pinning the
  drain paths: a failed submit must release its lease and error the
  query cleanly.

Everything is observable: ``metrics_samples()`` feeds /metrics,
``snapshot()`` rides the /select/logsql/active_queries payload, and
slot/queue waits land in the obs.hist histograms and ?trace=1 trees.
"""

from __future__ import annotations

from .admission import (AdmissionController, AdmissionShed, REASONS,
                        admission_snapshots, note_rejected)
from .admission import metrics_samples as _admission_metrics
from .netfaults import (FaultProxy, clear_net_faults, inject_net_fault,
                        maybe_fail_net)
from .scheduler import (DispatchScheduler, InjectedFaultError,
                        check_balanced, clear_faults, device_slots,
                        global_budget, inject_fault, maybe_fail_submit,
                        sched_enabled, scheduler, set_tenant_weight,
                        tenant_weight)
from .scheduler import metrics_samples as _scheduler_metrics

__all__ = [
    "AdmissionController", "AdmissionShed", "FaultProxy", "REASONS",
    "DispatchScheduler", "InjectedFaultError", "admission_snapshots",
    "check_balanced", "clear_faults", "clear_net_faults",
    "device_slots", "global_budget", "inject_fault", "inject_net_fault",
    "maybe_fail_net", "maybe_fail_submit", "metrics_samples",
    "note_rejected", "sched_enabled", "scheduler", "set_tenant_weight",
    "snapshot", "tenant_weight",
]


def metrics_samples() -> list[tuple[str, dict, float]]:
    """(base, labels, value) samples for server/app.py Metrics.render:
    dispatch-scheduler gauges + per-tenant admitted/shed counters +
    per-pool queue gauges."""
    return _scheduler_metrics() + _admission_metrics()


def snapshot() -> dict:
    """Live scheduler state for /select/logsql/active_queries."""
    return {"dispatch": scheduler().snapshot(),
            "admission": admission_snapshots()}
