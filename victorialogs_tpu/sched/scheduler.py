"""Shared device-dispatch scheduler: ONE global in-flight budget with
weighted fair queuing across active queries.

PR 3 gave every query its own ``VL_INFLIGHT`` dispatch window; PR 6
measured what that costs under concurrency (8 clients: p50 ~6.5x the
solo wall — every runner burns its own window and fights for the device
unmanaged).  This module makes the in-flight budget a SHARED resource:

- the process owns one :class:`DispatchScheduler` (``scheduler()``)
  with a global budget of ``VL_INFLIGHT_GLOBAL`` outstanding dispatch
  slots;
- each query's pipeline walk opens a :func:`device_slots` scope and
  LEASES a slot per submitted dispatch unit, releasing it when the
  unit's result is materialized (tpu/pipeline.py submit/harvest);
- when the budget is contended, a freed slot goes to the waiting query
  with the smallest weight-normalized in-flight count (round-robin on
  ties) — weighted max-min fair sharing, so one huge scan can no
  longer starve small queries, and tenants can be weighted
  (``VL_TENANT_WEIGHTS`` / the ``sched_config`` endpoint).

Lease discipline mirrors spans (obs/tracing.py) and activity records
(obs/activity.py): ``device_slots(...)`` is context-manager-only —
the with-block is what guarantees every lease this scope still holds
is released on EVERY exit path (limit, deadline, cancel, abandon and
fault-injection unwinds included), enforced by the vlint
``lease-discipline`` checker.  ``check_balanced()`` mirrors
StagingCache.check_balanced: with no queries running, the global
in-flight count must be exactly zero.

Fault injection (test-only): ``inject_fault()`` arms a one-shot
failure of a chosen upcoming dispatch submit; ``VL_FAULT_SUBMIT=p``
fails each submit with probability p.  Both raise
:class:`InjectedFaultError` from the pipeline's submit path, pinning
that a failed unit drains the window without downstream writes and
releases its lease (tests/test_sched.py).

Kill-switch: ``VL_SCHED=0`` grants every lease immediately (no budget,
no fairness) — the unmanaged PR 6 behavior, used as the bench baseline.

Lock order: the scheduler condition lock is a leaf — nothing is called
under it except flow bookkeeping; the waiter's ``check`` callback runs
with the lock held but only reads Events / raises (the processor-head
lock is never taken while a caller holds ours on the release side).
"""

from __future__ import annotations

import threading
import time
from .. import config

from ..obs import events


class InjectedFaultError(RuntimeError):
    """A dispatch submit failed via the fault-injection hook."""


def sched_enabled() -> bool:
    """VL_SCHED=0 disables the shared budget (leases grant instantly)."""
    return config.env_flag("VL_SCHED")


def global_budget() -> int:
    """VL_INFLIGHT_GLOBAL: max dispatch slots outstanding process-wide
    across ALL queries (>=1; default 8 = 2x the default per-query
    window, so a solo query never feels the scheduler)."""
    return max(1, config.env_int("VL_INFLIGHT_GLOBAL"))


# ---------------- tenant weights ----------------

_weights_mu = threading.Lock()
_weight_overrides: dict[str, float] = {}
_weights_env_cache: tuple[str, dict] | None = None


def set_tenant_weight(tenant: str, weight: float) -> None:
    """Runtime per-tenant fair-share weight (the POST sched_config
    endpoint); overrides VL_TENANT_WEIGHTS."""
    w = max(0.01, float(weight))
    with _weights_mu:
        _weight_overrides[str(tenant)] = w
    events.emit("sched_config", config_tenant=str(tenant), weight=w)


def tenant_weight(tenant: str) -> float:
    """Fair-share weight for one 'account:project' tenant (default 1.0;
    VL_TENANT_WEIGHTS="0:0=4,9:0=0.5" preseeds, sched_config updates)."""
    global _weights_env_cache
    env = config.env("VL_TENANT_WEIGHTS") or ""
    with _weights_mu:
        got = _weight_overrides.get(str(tenant))
        if got is not None:
            return got
        if _weights_env_cache is None or _weights_env_cache[0] != env:
            table: dict[str, float] = {}
            for item in env.split(","):
                if "=" not in item:
                    continue
                k, _, v = item.rpartition("=")
                try:
                    table[k.strip()] = max(0.01, float(v))
                except ValueError:
                    continue
            _weights_env_cache = (env, table)
        return _weights_env_cache[1].get(str(tenant), 1.0)


# ---------------- the scheduler ----------------

class _Flow:
    """One active query's fair-queuing state (shared by every
    device_slots scope of that query — partition workers attach to the
    same flow via refcount)."""

    __slots__ = ("key", "tenant", "weight", "held", "waiters", "refs",
                 "last_grant")

    def __init__(self, key, tenant: str, weight: float):
        self.key = key
        self.tenant = tenant
        self.weight = weight
        self.held = 0          # dispatch slots currently leased
        self.waiters = 0       # scopes blocked in acquire()
        self.refs = 0          # open device_slots scopes
        self.last_grant = 0    # grant sequence (round-robin tiebreak)


class DispatchScheduler:
    """The global dispatch-slot pool.  All state under one condition
    lock; grants happen inside ``_try_grant`` so the eligibility rule
    lives in exactly one place."""

    def __init__(self):
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._flows: dict = {}
        self._in_flight = 0
        self._grant_seq = 0
        self._grants_total = 0
        self._contended_total = 0

    # -- internal (callers hold self._mu) --

    def _flow_for(self, key, tenant: str, weight: float) -> _Flow:
        f = self._flows.get(key)
        if f is None:
            f = self._flows[key] = _Flow(key, tenant, weight)
        f.refs += 1
        return f

    def _deref(self, flow: _Flow) -> None:
        flow.refs -= 1
        if flow.refs <= 0:
            self._flows.pop(flow.key, None)

    def _eligible(self, flow: _Flow) -> bool:
        """Weighted max-min fairness: a waiting flow may take the next
        slot only if no OTHER waiting flow has a strictly smaller
        weight-normalized in-flight count (ties: least-recently
        granted first)."""
        best = None
        best_key = None
        for f in self._flows.values():
            if f.waiters <= 0 and f is not flow:
                continue
            k = (f.held / f.weight, f.last_grant)
            if best_key is None or k < best_key:
                best_key, best = k, f
        return best is None or best is flow

    def _try_grant(self, flow: _Flow) -> bool:
        if not sched_enabled():
            pass  # unmanaged: grant unconditionally (still counted)
        elif self._in_flight >= global_budget() or \
                not self._eligible(flow):
            return False
        self._in_flight += 1
        flow.held += 1
        self._grant_seq += 1
        flow.last_grant = self._grant_seq
        self._grants_total += 1
        return True

    # -- the lease API (context-manager-only, vlint lease-discipline) --

    def device_slots(self, act=None, tenant: str | None = None):
        """Open one query scope over the shared budget; the ONLY way to
        lease dispatch slots.  ``act`` is the query's activity record
        (flows of the same qid share fairness state across partition
        workers); tenant defaults to the record's."""
        return _SlotScope(self, act, tenant)

    # -- introspection --

    def check_balanced(self) -> bool:
        """True when every lease ever granted has been released and no
        query scope is still attached (mirrors
        StagingCache.check_balanced)."""
        with self._mu:
            return self._in_flight == 0 and not self._flows

    def snapshot(self) -> dict:
        with self._mu:
            flows = [{"key": str(f.key), "tenant": f.tenant,
                      "weight": f.weight, "held": f.held,
                      "waiting": f.waiters} for f in
                     self._flows.values()]
            return {"enabled": sched_enabled(),
                    "budget": global_budget(),
                    "in_flight": self._in_flight,
                    "grants_total": self._grants_total,
                    "contended_total": self._contended_total,
                    "flows": flows}


class _SlotScope:
    """Dynamic extent of one query scan's slot leases.  Releases every
    lease it still holds on exit — the drain path for cancel/deadline/
    fault unwinds — and detaches from the flow."""

    __slots__ = ("_s", "_act", "_tenant", "_flow", "_held")

    def __init__(self, s: DispatchScheduler, act, tenant):
        self._s = s
        self._act = act
        self._tenant = tenant
        self._flow = None
        self._held = 0

    def __enter__(self) -> "_SlotScope":
        act = self._act
        if self._tenant is None:
            self._tenant = getattr(act, "tenant", "0:0") or "0:0"
        key = act.qid if act is not None and \
            getattr(act, "enabled", False) else id(self)
        with self._s._cond:
            self._flow = self._s._flow_for(key, self._tenant,
                                           tenant_weight(self._tenant))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._s
        with s._cond:
            if self._held:
                # drain: the window was dropped mid-flight
                self._flow.held -= self._held
                s._in_flight -= self._held
                self._held = 0
            s._deref(self._flow)
            self._flow = None
            s._cond.notify_all()
        return False

    def try_acquire(self) -> bool:
        """Non-blocking lease; the pipeline's fast path (uncontended
        budget: identical behavior to the PR 6 per-query window)."""
        s = self._s
        with s._cond:
            if s._try_grant(self._flow):
                self._held += 1
                return True
            s._contended_total += 1
            return False

    def acquire(self, check=None, poll_s: float = 0.02) -> float:
        """Blocking lease: wait for this flow's fair turn.  ``check``
        runs every poll tick and may raise (deadline / cancellation) —
        the scope's __exit__ then releases everything.  Returns the
        wait in seconds."""
        t0 = time.perf_counter()
        s = self._s
        with s._cond:
            self._flow.waiters += 1
            try:
                while not s._try_grant(self._flow):
                    s._cond.wait(poll_s)
                    if check is not None:
                        check()
            finally:
                self._flow.waiters -= 1
            self._held += 1
        return time.perf_counter() - t0

    def release(self) -> None:
        """Return one leased slot (unit harvested)."""
        s = self._s
        with s._cond:
            if self._held <= 0:
                raise AssertionError(
                    "scheduler lease release without a held slot")
            self._held -= 1
            self._flow.held -= 1
            s._in_flight -= 1
            s._cond.notify_all()

    @property
    def held(self) -> int:
        with self._s._cond:
            return self._held


_scheduler = DispatchScheduler()


def scheduler() -> DispatchScheduler:
    """The process-global dispatch scheduler."""
    return _scheduler


def device_slots(act=None, tenant: str | None = None) -> _SlotScope:
    """Module-level convenience over ``scheduler().device_slots`` (the
    form the pipeline uses; context-manager-only)."""
    return _scheduler.device_slots(act, tenant)


def check_balanced() -> bool:
    return _scheduler.check_balanced()


# ---------------- fault injection (test-only drain-path hook) ----------------

_fault_mu = threading.Lock()
_fault_targets: list[int] = []
_submit_count = 0


def inject_fault(nth: int = 0) -> None:
    """Arm a one-shot submit failure: the (nth+1)-th dispatch submit
    from now raises InjectedFaultError.  Deterministic counterpart of
    VL_FAULT_SUBMIT for tests pinning the drain paths."""
    with _fault_mu:
        _fault_targets.append(_submit_count + 1 + max(0, int(nth)))


def clear_faults() -> None:
    with _fault_mu:
        _fault_targets.clear()


def maybe_fail_submit() -> None:
    """Called by the pipeline immediately before each dispatch submit.
    Raises InjectedFaultError for an armed inject_fault() target or
    with probability VL_FAULT_SUBMIT — AFTER the slot lease was taken,
    so the tests prove the lease is released on the error path."""
    global _submit_count
    with _fault_mu:
        _submit_count += 1
        n = _submit_count
        hit = n in _fault_targets
        if hit:
            _fault_targets.remove(n)
    if hit:
        # fault injections are journal events too: a chaos run's
        # injected failures correlate with the query_done error
        # records they caused, by qid/time
        events.emit("fault_injected", kind="submit", submit_no=n,
                    source="inject_fault")
        raise InjectedFaultError(
            f"injected dispatch submit fault (submit #{n})")
    p = config.env("VL_FAULT_SUBMIT") or ""
    if p:
        try:
            prob = float(p)
        except ValueError:
            prob = 0.0
        if prob > 0:
            import random
            if prob >= 1.0 or random.random() < prob:
                events.emit("fault_injected", kind="submit",
                            submit_no=n, source="VL_FAULT_SUBMIT")
                raise InjectedFaultError(
                    f"injected dispatch submit fault "
                    f"(VL_FAULT_SUBMIT={prob})")


def metrics_samples() -> list[tuple[str, dict, float]]:
    """Dispatch-scheduler samples for Metrics.render."""
    snap = _scheduler.snapshot()
    return [
        ("vl_sched_dispatch_budget", {}, snap["budget"]),
        ("vl_sched_dispatch_in_flight", {}, snap["in_flight"]),
        ("vl_sched_dispatch_grants_total", {}, snap["grants_total"]),
        ("vl_sched_dispatch_contended_total", {},
         snap["contended_total"]),
    ]
