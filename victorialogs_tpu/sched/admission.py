"""Admission control at the query entry: per-tenant concurrency and
bytes-in-flight limits, a bounded wait queue, deadline-aware shedding.

Replaces the raw FIFO ``threading.Semaphore`` gates in server/app.py.
The reference survives production traffic by gating everything behind
httpserver concurrency limiters (PAPER.md L6/L1); this is that gate,
with the three behaviors a saturated server needs:

- **shed, don't queue forever** — over-limit arrivals get 429 +
  ``Retry-After`` with a machine-readable reason
  (``tenant_limit`` / ``queue_full`` / ``deadline``) instead of an
  unbounded queue: the bounded queue (``VL_QUEUE_MAX``) absorbs
  bursts, everything past it sheds immediately;
- **per-tenant limits** — concurrency (``VL_TENANT_MAX_CONCURRENT``,
  runtime-overridable per tenant via the POST ``sched_config``
  endpoint) and estimated bytes-in-flight (``VL_TENANT_MAX_BYTES``,
  from the per-endpoint bytes-scanned EWMA) so one tenant cannot
  occupy the whole server;
- **deadline awareness** — a query that must queue is shed up front
  when the duration EWMA says its deadline cannot be met (queue wait
  estimate + run estimate > remaining budget), and a queued entry
  whose deadline passes while waiting sheds instead of running a
  walk that is already dead.

Queued-but-not-admitted queries are CANCELLABLE: the wait loop polls
the activity record's cancel flag (``cancel_query`` by qid — the
record registers BEFORE admission, phase "queued") and an optional
peer-disconnect probe, removing the entry from the queue before any
device work starts.

``admit(...)`` is context-manager-only: the with-block is what
decrements the concurrency/bytes accounting on every exit path and
feeds the duration/bytes EWMAs on completion.

Lock order: the controller condition lock is a leaf; the wait loop's
cancel/disconnect probes only read an Event / poll a socket.  The
activity record's own lock is never taken under ours (abandon/phase
updates happen outside the controller lock).
"""

from __future__ import annotations

import threading
import time
import weakref

from .. import config
from ..obs import events, hist

REASONS = ("tenant_limit", "queue_full", "deadline", "cancelled")

_EWMA = 0.3

# endpoints whose admission extent is a CONNECTION lifetime, not a
# query execution: feeding their wall time into the duration EWMA
# would poison the deadline-feasibility gate (a 10-minute tail would
# make every queued tail look infeasible) — same exclusion
# server/app.py applies to vl_query_duration_seconds
_LIFETIME_ENDPOINTS = frozenset(("/select/logsql/tail",))

# tenant label values and endpoint paths come from the client: both
# accounting keyspaces are hard-capped, overflow aggregating into one
# slot, so header/path cycling can neither leak memory nor explode
# /metrics cardinality (mirrors obs/activity._TENANT_MAX)
_TENANT_MAX = 1024
_ENDPOINT_MAX = 64
_OVERFLOW = "other"


def _capped_key(table: dict, key: str, cap: int) -> str:
    if key in table or len(table) < cap:
        return key
    return _OVERFLOW


class AdmissionShed(Exception):
    """A query was refused admission.  ``reason`` is machine-readable
    (tenant_limit | queue_full | deadline, plus cancelled for a queued
    entry killed before it started); ``retry_after`` feeds the
    Retry-After response header.  ``limit``/``current`` (when known)
    feed the X-VL-Concurrency-Limit/-Current response headers so
    clients (vlagent) can back off adaptively instead of sleeping a
    fixed Retry-After — the reference's X-Concurrency hint style."""

    def __init__(self, reason: str, message: str,
                 retry_after: float | None = 1.0, status: int = 429,
                 limit: int | None = None, current: int | None = None):
        super().__init__(message)
        self.reason = reason
        self.message = message
        self.retry_after = retry_after
        self.status = status
        self.limit = limit
        self.current = current


# ---------------- process-global admitted/shed accounting ----------------

_acct_mu = threading.Lock()
# (pool, reason, tenant) -> n — the pool label keeps a combined
# frontend+storage node's internal-pool sub-query sheds/admits from
# double-counting into the client-facing select series
_rejected: dict[tuple[str, str, str], int] = {}
_admitted: dict[tuple[str, str], int] = {}   # (pool, tenant) -> n
# persistent capped tenant keyspaces (O(1) on the shedding hot path)
_rejected_tenants: set = set()
_admitted_tenants: set = set()
_controllers: "weakref.WeakSet[AdmissionController]" = weakref.WeakSet()


def _capped_tenant(tenants: set, tenant: str) -> str:
    if tenant not in tenants:
        if len(tenants) >= _TENANT_MAX:
            tenant = _OVERFLOW
        tenants.add(tenant)
    return tenant


def note_rejected(tenant: str, reason: str,
                  pool: str = "select") -> None:
    with _acct_mu:
        key = (pool, reason, _capped_tenant(_rejected_tenants, tenant))
        _rejected[key] = _rejected.get(key, 0) + 1


def _note_admitted(tenant: str, pool: str = "select") -> None:
    with _acct_mu:
        key = (pool, _capped_tenant(_admitted_tenants, tenant))
        _admitted[key] = _admitted.get(key, 0) + 1


def metrics_samples() -> list[tuple[str, dict, float]]:
    """Admission samples for Metrics.render: per-tenant admitted/shed
    counters plus live queue-depth/active gauges per pool."""
    out: list[tuple[str, dict, float]] = []
    with _acct_mu:
        rejected = dict(_rejected)
        admitted = dict(_admitted)
        ctls = list(_controllers)
    for (pool, reason, tenant), n in sorted(rejected.items()):
        out.append(("vl_select_rejected_total",
                    {"pool": pool, "reason": reason, "tenant": tenant},
                    n))
    for (pool, tenant), n in sorted(admitted.items()):
        out.append(("vl_select_admitted_total",
                    {"pool": pool, "tenant": tenant}, n))
    for c in ctls:
        snap = c.snapshot()
        lbl = {"pool": snap["pool"]}
        out.append(("vl_sched_queue_depth", lbl, snap["queued"]))
        out.append(("vl_sched_admission_active", lbl, snap["active"]))
    return out


def admission_snapshots() -> list[dict]:
    with _acct_mu:
        ctls = list(_controllers)
    return [c.snapshot() for c in ctls]


# ---------------- the controller ----------------

class _Waiter:
    __slots__ = ("tenant", "endpoint", "granted", "shed_reason", "dead",
                 "deadline", "est_bytes")

    def __init__(self, tenant: str, endpoint: str,
                 deadline: float | None):
        self.tenant = tenant
        self.endpoint = endpoint
        self.granted = False
        self.shed_reason: str | None = None
        self.dead = False
        self.deadline = deadline      # monotonic, None = no deadline
        self.est_bytes = 0            # reserved at grant time


class AdmissionController:
    """One admission pool (the single binary runs two: ``select`` for
    client queries, ``internal`` for cluster sub-queries, so a node
    acting as both frontend and storage node can't starve the
    sub-queries it fans out itself)."""

    def __init__(self, max_concurrent: int | None = None,
                 queue_timeout_s: float | None = None,
                 pool: str = "select"):
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self.pool = pool
        self._max = max_concurrent if max_concurrent else \
            config.env_int("VL_MAX_CONCURRENT")
        if queue_timeout_s is None:
            queue_timeout_s = \
                config.env_int("VL_QUEUE_TIMEOUT_MS") / 1e3
        self.queue_timeout_s = queue_timeout_s
        self._queue_max = config.env_int("VL_QUEUE_MAX",
                                         2 * self._max)
        self._tenant_max_default = \
            config.env_int("VL_TENANT_MAX_CONCURRENT") or self._max
        self._tenant_max_bytes = config.env_int("VL_TENANT_MAX_BYTES")
        self._tenant_limits: dict[str, int] = {}
        self._active = 0
        self._tenant_active: dict[str, int] = {}
        self._tenant_bytes: dict[str, int] = {}   # estimated, in flight
        self._queue: list[_Waiter] = []
        # per-endpoint completion EWMAs: the deadline-feasibility and
        # bytes-in-flight estimators (fed on every admitted exit)
        self._dur_ewma: dict[str, float] = {}
        self._bytes_ewma: dict[str, float] = {}
        with _acct_mu:
            _controllers.add(self)

    # -- runtime config (POST sched_config) --

    def set_tenant_limit(self, tenant: str, max_concurrent: int) -> None:
        with self._cond:
            if max_concurrent <= 0:
                self._tenant_limits.pop(tenant, None)
            else:
                self._tenant_limits[tenant] = max_concurrent
        # config changes are audit events: who got capped to what,
        # queryable from the journal long after the fact
        events.emit("sched_config", pool=self.pool,
                    config_tenant=str(tenant),
                    max_concurrent=max_concurrent)

    def _tenant_cap(self, tenant: str) -> int:
        return self._tenant_limits.get(tenant, self._tenant_max_default)

    # -- estimators (callers hold self._mu) --

    def _run_estimate(self, endpoint: str) -> float:
        return self._dur_ewma.get(endpoint, 0.0)

    def _bytes_estimate(self, endpoint: str) -> int:
        return int(self._bytes_ewma.get(endpoint, 0.0))

    def _note_done(self, endpoint: str, duration: float,
                   nbytes: int) -> None:
        if endpoint in _LIFETIME_ENDPOINTS:
            # a connection's lifetime is not a query's run time: one
            # long tail must not convince the deadline gate that every
            # queued tail is infeasible
            return
        # streaming endpoints measure response DRAIN time too (a slow
        # client inflates the wall); clamping each observation at the
        # queue timeout bounds how far any stalled consumer can push
        # the feasibility estimate
        duration = min(duration, self.queue_timeout_s)
        endpoint = _capped_key(self._dur_ewma, endpoint, _ENDPOINT_MAX)
        old = self._dur_ewma.get(endpoint)
        self._dur_ewma[endpoint] = duration if old is None else \
            old + _EWMA * (duration - old)
        oldb = self._bytes_ewma.get(endpoint)
        self._bytes_ewma[endpoint] = nbytes if oldb is None else \
            oldb + _EWMA * (nbytes - oldb)

    def _grant_waiters(self) -> None:
        """Hand freed capacity to the queue head(s), FIFO; entries whose
        tenant filled up — concurrency OR bytes budget — while they
        waited shed with tenant_limit (callers hold self._mu and notify
        after).  The bytes estimate is RESERVED here, at grant, so two
        waiters granted in one pass cannot jointly overshoot the
        budget."""
        while self._queue and self._active < self._max:
            w = self._queue[0]
            if w.dead:
                self._queue.pop(0)
                continue
            if self._tenant_active.get(w.tenant, 0) >= \
                    self._tenant_cap(w.tenant):
                w.shed_reason = "tenant_limit"
                self._queue.pop(0)
                continue
            est = self._bytes_estimate(w.endpoint)
            if self._tenant_max_bytes > 0 and est and \
                    self._tenant_bytes.get(w.tenant, 0) + est > \
                    self._tenant_max_bytes:
                w.shed_reason = "tenant_limit"
                self._queue.pop(0)
                continue
            w.granted = True
            w.est_bytes = est
            if est:
                self._tenant_bytes[w.tenant] = \
                    self._tenant_bytes.get(w.tenant, 0) + est
            self._active += 1
            self._tenant_active[w.tenant] = \
                self._tenant_active.get(w.tenant, 0) + 1
            self._queue.pop(0)

    # -- the admission API (context-manager-only) --

    def admit(self, tenant: str = "0:0", endpoint: str = "",
              deadline_s: float | None = None, act=None,
              disconnected=None) -> "_Admission":
        """Admit one query for its dynamic extent or raise
        AdmissionShed.  ``deadline_s`` is the request's remaining time
        budget; ``act`` (activity record) makes the queued entry
        cancellable by qid; ``disconnected()`` polls the HTTP peer."""
        return _Admission(self, str(tenant), endpoint, deadline_s, act,
                          disconnected)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "pool": self.pool,
                "max_concurrent": self._max,
                "active": self._active,
                "queued": sum(1 for w in self._queue if not w.dead),
                "queue_max": self._queue_max,
                "queue_timeout_s": self.queue_timeout_s,
                "tenant_active": {t: n for t, n in
                                  sorted(self._tenant_active.items())
                                  if n},
                "tenant_limits": dict(self._tenant_limits),
            }


class _Admission:
    """Dynamic extent of one admitted query: concurrency/bytes
    accounting on enter, release + EWMA feed on EVERY exit path."""

    __slots__ = ("_c", "_tenant", "_endpoint", "_deadline_s", "_act",
                 "_disconnected", "_t_admit", "_est_bytes")

    def __init__(self, c: AdmissionController, tenant: str,
                 endpoint: str, deadline_s, act, disconnected):
        self._c = c
        self._tenant = tenant
        self._endpoint = endpoint
        self._deadline_s = deadline_s
        self._act = act
        self._disconnected = disconnected
        self._t_admit = 0.0
        self._est_bytes = 0

    def _shed(self, reason: str, message: str, retry_after: float,
              limit: int | None = None,
              current: int | None = None) -> AdmissionShed:
        c = self._c
        if limit is None:
            limit = c._max
        if current is None:
            current = c._active
        note_rejected(self._tenant, reason, pool=c.pool)
        # sheds are exactly what the self-telemetry journal exists to
        # record: `tail` them live, stats-pipe them by tenant/reason
        # over hours.  Journal ingest bypasses admission entirely, so
        # this event survives the very overload it reports.
        events.emit("admission_shed", tenant=self._tenant,
                    reason=reason, endpoint=self._endpoint, pool=c.pool,
                    limit=limit, current=current,
                    retry_after_s=round(retry_after or 0.0, 3))
        return AdmissionShed(reason, message, retry_after=retry_after,
                             limit=limit, current=current)

    def _cancel_probe(self) -> str | None:
        """'cancelled' / 'abandoned' when the queued entry should leave
        the queue before any work starts (called WITHOUT the controller
        lock held)."""
        act = self._act
        if act is not None and getattr(act, "enabled", False) and \
                act.is_cancelled():
            return "cancelled"
        if self._disconnected is not None and self._disconnected():
            return "abandoned"
        return None

    def __enter__(self) -> "_Admission":
        c = self._c
        t0 = time.monotonic()
        deadline = None if self._deadline_s is None else \
            t0 + self._deadline_s
        with c._cond:
            cap = c._tenant_cap(self._tenant)
            if c._tenant_active.get(self._tenant, 0) >= cap:
                raise self._shed(
                    "tenant_limit",
                    f"tenant {self._tenant} at its concurrency limit "
                    f"({cap}); adjust VL_TENANT_MAX_CONCURRENT or the "
                    f"sched_config override",
                    retry_after=max(1.0, c._run_estimate(self._endpoint)),
                    limit=cap,
                    current=c._tenant_active.get(self._tenant, 0))
            if c._tenant_max_bytes > 0:
                est = c._bytes_estimate(self._endpoint)
                if c._tenant_bytes.get(self._tenant, 0) + est > \
                        c._tenant_max_bytes:
                    raise self._shed(
                        "tenant_limit",
                        f"tenant {self._tenant} over its bytes-in-"
                        f"flight budget (VL_TENANT_MAX_BYTES="
                        f"{c._tenant_max_bytes})",
                        retry_after=max(
                            1.0, c._run_estimate(self._endpoint)))
            if c._active < c._max and not c._queue:
                self._grant_locked()
                # reserve the bytes estimate under the SAME lock as the
                # grant so concurrent admits cannot jointly overshoot
                # the tenant budget
                self._est_bytes = c._bytes_estimate(self._endpoint)
                if self._est_bytes:
                    c._tenant_bytes[self._tenant] = \
                        c._tenant_bytes.get(self._tenant, 0) + \
                        self._est_bytes
                w = None
            else:
                w = self._enqueue_locked(deadline)
        if w is None:
            return self._admitted(0.0)
        try:
            waited = self._wait(w, t0)
        except BaseException:
            with c._cond:
                if w.granted:
                    # raced a concurrent grant (e.g. KeyboardInterrupt
                    # landing between the grant and the waiter's next
                    # poll): fold the slot AND its bytes reservation
                    # back or the pool shrinks permanently
                    self._est_bytes = w.est_bytes
                    self._release_locked()
                    w.granted = False
                w.dead = True
                c._grant_waiters()
                c._cond.notify_all()
            raise
        self._est_bytes = w.est_bytes
        return self._admitted(waited)

    def _enqueue_locked(self, deadline) -> _Waiter:
        """Queue-entry gate (caller holds c._mu): shed up front what
        provably cannot finish, bound the queue, else join it."""
        c = self._c
        est_run = c._run_estimate(self._endpoint)
        depth = sum(1 for w in c._queue if not w.dead)
        if self._deadline_s is not None:
            # shed only on the PROVABLE part: the queue wait ahead of
            # us.  Folding est_run into the comparison would let a
            # drain-inflated EWMA (slow clients) reject queries the
            # server could execute in milliseconds; a genuinely slow
            # execution still dies on its own deadline downstream.
            est_wait = est_run * (depth + 1) / max(c._max, 1)
            if self._deadline_s <= 0 or (
                    est_run > 0 and est_wait > self._deadline_s):
                raise self._shed(
                    "deadline",
                    f"deadline {self._deadline_s:.3f}s cannot be "
                    f"met (estimated queue wait {est_wait:.3f}s, "
                    f"per-query estimate {est_run:.3f}s)",
                    retry_after=max(1.0, est_wait))
        if depth >= c._queue_max:
            raise self._shed(
                "queue_full",
                f"admission queue full ({c._queue_max} waiting); "
                f"too many concurrent queries",
                retry_after=max(1.0, est_run * depth /
                                max(c._max, 1)))
        w = _Waiter(self._tenant, self._endpoint, deadline)
        c._queue.append(w)
        return w

    def _wait(self, w: _Waiter, t0: float) -> float:
        """Poll loop for one queued entry; returns the wait duration or
        raises AdmissionShed (granted/shed state transitions happen
        under the controller lock; cancel/disconnect probes outside)."""
        c = self._c
        while True:
            with c._cond:
                c._grant_waiters()
                if w.granted:
                    return time.monotonic() - t0
                if w.shed_reason:
                    raise self._shed(
                        w.shed_reason,
                        f"shed while queued ({w.shed_reason})",
                        retry_after=max(
                            1.0, c._run_estimate(self._endpoint)))
                now = time.monotonic()
                if w.deadline is not None and now >= w.deadline:
                    w.dead = True
                    raise self._shed(
                        "deadline",
                        "deadline expired while queued",
                        retry_after=None)
                if now - t0 >= c.queue_timeout_s:
                    w.dead = True
                    raise self._shed(
                        "queue_full",
                        f"query queued longer than "
                        f"-search.maxQueueDuration="
                        f"{c.queue_timeout_s}s; too many concurrent "
                        f"queries",
                        retry_after=max(
                            1.0, c._run_estimate(self._endpoint)))
                c._cond.wait(0.05)
            why = self._cancel_probe()
            if why is not None:
                with c._cond:
                    if w.granted:
                        # raced a grant: fold it (incl. the bytes
                        # reservation) back before leaving — and clear
                        # the flag so the caller's unwind handler
                        # can't fold it back twice
                        self._est_bytes = w.est_bytes
                        self._release_locked()
                        w.granted = False
                    w.dead = True
                    c._grant_waiters()
                    c._cond.notify_all()
                if why == "abandoned":
                    act = self._act
                    if act is not None:
                        act.abandon()
                note_rejected(self._tenant, "cancelled",
                              pool=c.pool)
                events.emit("admission_shed", tenant=self._tenant,
                            reason="cancelled",
                            endpoint=self._endpoint, pool=c.pool)
                raise AdmissionShed(
                    "cancelled",
                    "query cancelled while queued for admission",
                    retry_after=None, status=499)

    # -- bookkeeping (callers hold c._mu unless noted) --

    def _grant_locked(self) -> None:
        c = self._c
        c._active += 1
        c._tenant_active[self._tenant] = \
            c._tenant_active.get(self._tenant, 0) + 1

    def _release_locked(self) -> None:
        c = self._c
        c._active -= 1
        n = c._tenant_active.get(self._tenant, 1) - 1
        if n:
            c._tenant_active[self._tenant] = n
        else:
            c._tenant_active.pop(self._tenant, None)
        if self._est_bytes:
            b = c._tenant_bytes.get(self._tenant, 0) - self._est_bytes
            if b > 0:
                c._tenant_bytes[self._tenant] = b
            else:
                c._tenant_bytes.pop(self._tenant, None)

    def _admitted(self, waited: float) -> "_Admission":
        # the bytes reservation happened AT GRANT (immediate path: in
        # __enter__ under the grant lock; queued path: _grant_waiters)
        # so concurrent grants cannot jointly overshoot the budget
        c = self._c
        hist.SCHED_QUEUE_WAIT.observe(waited)
        _note_admitted(self._tenant, pool=c.pool)
        self._t_admit = time.monotonic()
        act = self._act
        if act is not None and getattr(act, "enabled", False) and waited:
            act.set("admission_wait_s", round(waited, 6))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        c = self._c
        duration = time.monotonic() - self._t_admit
        nbytes = 0
        act = self._act
        if act is not None and getattr(act, "enabled", False):
            nbytes = act.counter("bytes_scanned")
            exec_mono = getattr(act, "exec_mono", None)
            if exec_mono is not None:
                # sink-side exec/drain split (obs/activity
                # mark_exec_done): the EWMA feeds on EXECUTION time
                # only, so a stalled streaming client's drain cannot
                # poison deadline feasibility for everyone queued
                # behind it.  (_note_done's queue-timeout clamp stays
                # as defense for records without the stamp.)  The
                # record also carries predicted_duration_s — the
                # per-QUERY priced estimate (obs/explain) this
                # per-endpoint EWMA could be upgraded to consume.
                duration = min(duration,
                               max(exec_mono - self._t_admit, 0.0))
        with c._cond:
            self._release_locked()
            c._note_done(self._endpoint, duration, nbytes)
            c._grant_waiters()
            c._cond.notify_all()
        return False
