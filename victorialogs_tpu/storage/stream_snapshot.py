"""Columnar stream-index snapshot: the compacted base of the stream index.

The reference backs its stream index with a mergeset LSM
(vendor/.../lib/mergeset/table.go: sorted immutable parts + background
merges + binary-searched lookups).  This module is that idea reduced to the
per-day partition lifecycle: the append-only registration log compacts into
ONE immutable sorted columnar snapshot (at close, or when the tail grows
past a threshold), and reopen becomes a bulk numpy load — O(streams) bytes,
near-zero Python-object work — instead of a JSON replay that rebuilds every
posting set eagerly.

Layout (single zstd-framed file, `streams.snap`):
- streams sorted by (tenant, hi, lo): u32 tenant_idx[], u64 hi[], u64 lo[],
  tags offsets into one utf-8 blob — membership and tag lookups are
  binary searches, no per-stream Python objects at load;
- per (tenant, label): a sorted fixed-width bytes table of the label's
  values (searchsorted for '=' lookups, linear decode only for regex
  filters) with each value's posting list as a slice of one u32 stream-
  index blob, plus the label's "any" posting list.  Posting sets
  materialize lazily per (label, value) on first query and are memoized.

Crash safety: the snapshot is written tmp+fsync+rename and records the log
byte offset it covers; reopen loads the snapshot and replays only the log
tail past that offset.  A torn snapshot is discarded (full log replay
still works — the log is never truncated).
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from ..utils import zstd as _zstd
from .log_rows import StreamID, TenantID
from .stream_filter import parse_stream_tags

SNAP_MAGIC = b"VLSNAP1\n"


def _pack_arrays(arrays: dict) -> tuple[dict, bytes]:
    meta = {}
    blobs = []
    off = 0
    for name, arr in arrays.items():
        raw = arr.tobytes() if isinstance(arr, np.ndarray) else arr
        meta[name] = {
            "off": off, "len": len(raw),
            "dtype": str(arr.dtype) if isinstance(arr, np.ndarray)
            else "bytes",
        }
        blobs.append(raw)
        off += len(raw)
    return meta, b"".join(blobs)


def write_snapshot(path: str, streams: dict, log_offset: int) -> None:
    """streams: StreamID -> tags_str (any order); atomic tmp+rename."""
    items = sorted(
        ((sid.tenant.account_id, sid.tenant.project_id, sid.hi, sid.lo,
          tags) for sid, tags in streams.items()))
    n = len(items)
    tenants: list[tuple[int, int]] = []
    tenant_idx_of: dict[tuple[int, int], int] = {}
    t_idx = np.empty(n, dtype=np.uint32)
    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    tag_off = np.empty(n + 1, dtype=np.uint64)
    tag_parts = []
    pos = 0
    for i, (a, p, h, lw, tags) in enumerate(items):
        key = (a, p)
        ti = tenant_idx_of.get(key)
        if ti is None:
            ti = tenant_idx_of[key] = len(tenants)
            tenants.append(key)
        t_idx[i] = ti
        hi[i] = h
        lo[i] = lw
        tag_off[i] = pos
        b = tags.encode("utf-8")
        tag_parts.append(b)
        pos += len(b)
    tag_off[n] = pos

    # per (tenant, label): value -> [stream indices]; label -> any indices
    post: dict = {}
    for i, (a, p, _h, _l, tags) in enumerate(items):
        ti = tenant_idx_of[(a, p)]
        per = post.setdefault(ti, {})
        for label, value in parse_stream_tags(tags).items():
            lab = per.setdefault(label, {})
            lab.setdefault(value, []).append(i)

    arrays = {"t_idx": t_idx, "hi": hi, "lo": lo, "tag_off": tag_off,
              "tags_blob": b"".join(tag_parts)}
    labels_meta: dict = {}
    for ti, per in post.items():
        for label, values in per.items():
            vkeys = sorted(values, key=lambda v: v.encode("utf-8"))
            vbytes = [v.encode("utf-8") for v in vkeys]
            w = max((len(b) for b in vbytes), default=1) or 1
            vtab = np.zeros((len(vkeys),), dtype=f"S{w}")
            counts = np.empty(len(vkeys), dtype=np.uint32)
            idx_chunks = []
            any_set = set()
            for k, (vk, vb) in enumerate(zip(vkeys, vbytes)):
                vtab[k] = vb
                ids = values[vk]
                counts[k] = len(ids)
                idx_chunks.append(np.asarray(ids, dtype=np.uint32))
                any_set.update(ids)
            idx_blob = np.concatenate(idx_chunks) if idx_chunks else \
                np.empty(0, dtype=np.uint32)
            any_arr = np.fromiter(sorted(any_set), dtype=np.uint32,
                                  count=len(any_set))
            base = f"p{ti}:{label}"
            arrays[base + ":v"] = vtab
            arrays[base + ":c"] = counts
            arrays[base + ":i"] = idx_blob
            arrays[base + ":a"] = any_arr
            labels_meta.setdefault(str(ti), {})[label] = {"w": w}

    ameta, blob = _pack_arrays(arrays)
    header = json.dumps({
        "n": n, "tenants": tenants, "arrays": ameta,
        "labels": labels_meta, "log_offset": log_offset,
    }, separators=(",", ":")).encode("utf-8")
    payload = _zstd.compress(
        struct.pack(">I", len(header)) + header + blob, level=3)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SNAP_MAGIC)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _LabelPostings:
    """Lazy accessor for one (tenant, label)'s posting tables."""

    __slots__ = ("values", "counts", "idx_starts", "idx_blob", "any_idx",
                 "_decoded")

    def __init__(self, values, counts, idx_blob, any_idx):
        self.values = values                     # S-array, sorted
        self.counts = counts
        self.idx_starts = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=self.idx_starts[1:])
        self.idx_blob = idx_blob
        self.any_idx = any_idx
        self._decoded: list | None = None

    def lookup(self, value: str) -> np.ndarray:
        """Stream indices for label == value (empty if absent)."""
        vb = value.encode("utf-8")
        if len(vb) > self.values.dtype.itemsize:
            return np.empty(0, dtype=np.uint32)
        k = np.searchsorted(self.values, np.bytes_(vb))
        if k >= len(self.values) or self.values[k] != vb:
            return np.empty(0, dtype=np.uint32)
        return self.idx_blob[self.idx_starts[k]:self.idx_starts[k + 1]]

    def items(self):
        """(value_str, indices) pairs — regex filters walk all values."""
        if self._decoded is None:
            self._decoded = [v.decode("utf-8") for v in self.values]
        for k, v in enumerate(self._decoded):
            yield v, self.idx_blob[self.idx_starts[k]:
                                   self.idx_starts[k + 1]]


class StreamSnapshot:
    """Read-only view over one snapshot file."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            magic = f.read(len(SNAP_MAGIC))
            if magic != SNAP_MAGIC:
                raise ValueError("bad snapshot magic")
            raw = _zstd.decompress(f.read(), max_output_size=1 << 33)
        hlen = struct.unpack(">I", raw[:4])[0]
        hdr = json.loads(raw[4:4 + hlen])
        blob = memoryview(raw)[4 + hlen:]
        self.n: int = hdr["n"]
        self.log_offset: int = hdr["log_offset"]
        self.tenants = [TenantID(a, p) for a, p in hdr["tenants"]]
        self._tenant_idx = {t: i for i, t in enumerate(self.tenants)}
        arrays = {}
        for name, m in hdr["arrays"].items():
            seg = blob[m["off"]:m["off"] + m["len"]]
            arrays[name] = bytes(seg) if m["dtype"] == "bytes" else \
                np.frombuffer(seg, dtype=m["dtype"])
        self.t_idx = arrays["t_idx"]
        self.hi = arrays["hi"]
        self.lo = arrays["lo"]
        self.tag_off = arrays["tag_off"]
        self.tags_blob = arrays["tags_blob"]
        self._labels_meta = hdr["labels"]
        self._arrays = arrays
        self._postings_cache: dict = {}
        # rows are sorted by (tenant, hi, lo): per-tenant contiguous slices
        self._tenant_bounds = np.searchsorted(
            self.t_idx, np.arange(len(self.tenants) + 1, dtype=np.uint32))

    # ---- registry lookups ----
    def find(self, sid: StreamID) -> int:
        """Row index of sid, or -1."""
        ti = self._tenant_idx.get(sid.tenant)
        if ti is None:
            return -1
        s, e = int(self._tenant_bounds[ti]), int(self._tenant_bounds[ti + 1])
        h = np.uint64(sid.hi)
        i = s + int(np.searchsorted(self.hi[s:e], h))
        while i < e and self.hi[i] == h:
            if int(self.lo[i]) == sid.lo:
                return i
            if int(self.lo[i]) > sid.lo:
                return -1
            i += 1
        return -1

    def tags_at(self, i: int) -> str:
        a, b = int(self.tag_off[i]), int(self.tag_off[i + 1])
        return self.tags_blob[a:b].decode("utf-8")

    def stream_at(self, i: int) -> StreamID:
        return StreamID(self.tenants[int(self.t_idx[i])],
                        int(self.hi[i]), int(self.lo[i]))

    def streams_at(self, idxs) -> list:
        """Bulk StreamID materialization (tolist() beats per-element numpy
        indexing ~3x; only FINAL query results pay this)."""
        tis = self.t_idx[idxs].tolist()
        his = self.hi[idxs].tolist()
        los = self.lo[idxs].tolist()
        tenants = self.tenants
        return [StreamID(tenants[t], h, lw)
                for t, h, lw in zip(tis, his, los)]

    def tenant_range(self, tenant: TenantID) -> tuple[int, int]:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return (0, 0)
        return (int(self._tenant_bounds[ti]),
                int(self._tenant_bounds[ti + 1]))

    # ---- postings ----
    def label_postings(self, tenant: TenantID,
                       label: str) -> _LabelPostings | None:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return None
        key = (ti, label)
        got = self._postings_cache.get(key)
        if got is not None:
            return got
        if label not in self._labels_meta.get(str(ti), {}):
            return None
        base = f"p{ti}:{label}"
        lp = _LabelPostings(self._arrays[base + ":v"],
                            self._arrays[base + ":c"],
                            self._arrays[base + ":i"],
                            self._arrays[base + ":a"])
        self._postings_cache[key] = lp
        return lp
