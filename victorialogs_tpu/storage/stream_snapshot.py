"""Columnar stream-index snapshot: the compacted base of the stream index.

The reference backs its stream index with a mergeset LSM
(vendor/.../lib/mergeset/table.go: sorted immutable parts + background
merges + binary-searched lookups).  This module is that idea reduced to the
per-day partition lifecycle: the append-only registration log compacts into
ONE immutable sorted columnar snapshot (at close, or when the tail grows
past a threshold), and reopen becomes a bulk numpy load — O(streams) bytes,
near-zero Python-object work — instead of a JSON replay that rebuilds every
posting set eagerly.

Layout (single zstd-framed file, `streams.snap`):
- streams sorted by (tenant, hi, lo): u32 tenant_idx[], u64 hi[], u64 lo[],
  tags offsets into one utf-8 blob — membership and tag lookups are
  binary searches, no per-stream Python objects at load;
- per (tenant, label): a sorted fixed-width bytes table of the label's
  values (searchsorted for '=' lookups, linear decode only for regex
  filters) with each value's posting list as a slice of one u32 stream-
  index blob, plus the label's "any" posting list.  Posting sets
  materialize lazily per (label, value) on first query and are memoized.

Crash safety: the snapshot is written tmp+fsync+rename and records the log
byte offset it covers; reopen loads the snapshot and replays only the log
tail past that offset.  A torn snapshot is discarded (full log replay
still works — the log is never truncated).
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from ..utils import zstd as _zstd
from .log_rows import StreamID, TenantID
from .stream_filter import parse_stream_tags

SNAP_MAGIC = b"VLSNAP1\n"


def _pack_arrays(arrays: dict) -> tuple[dict, bytes]:
    meta = {}
    blobs = []
    off = 0
    for name, arr in arrays.items():
        raw = arr.tobytes() if isinstance(arr, np.ndarray) else arr
        meta[name] = {
            "off": off, "len": len(raw),
            "dtype": str(arr.dtype) if isinstance(arr, np.ndarray)
            else "bytes",
        }
        blobs.append(raw)
        off += len(raw)
    return meta, b"".join(blobs)


def _finish_snapshot(path: str, arrays: dict, n: int, tenants: list,
                     labels_meta: dict, log_offset: int) -> None:
    ameta, blob = _pack_arrays(arrays)
    header = json.dumps({
        "n": n, "tenants": tenants, "arrays": ameta,
        "labels": labels_meta, "log_offset": log_offset,
    }, separators=(",", ":")).encode("utf-8")
    payload = _zstd.compress(
        struct.pack(">I", len(header)) + header + blob, level=3)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SNAP_MAGIC)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_snapshot(path: str, streams: dict, log_offset: int) -> None:
    """streams: StreamID -> tags_str (any order); atomic tmp+rename."""
    items = sorted(
        ((sid.tenant.account_id, sid.tenant.project_id, sid.hi, sid.lo,
          tags) for sid, tags in streams.items()))
    n = len(items)
    tenants: list[tuple[int, int]] = []
    tenant_idx_of: dict[tuple[int, int], int] = {}
    t_idx = np.empty(n, dtype=np.uint32)
    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    tag_off = np.empty(n + 1, dtype=np.uint64)
    tag_parts = []
    pos = 0
    for i, (a, p, h, lw, tags) in enumerate(items):
        key = (a, p)
        ti = tenant_idx_of.get(key)
        if ti is None:
            ti = tenant_idx_of[key] = len(tenants)
            tenants.append(key)
        t_idx[i] = ti
        hi[i] = h
        lo[i] = lw
        tag_off[i] = pos
        b = tags.encode("utf-8")
        tag_parts.append(b)
        pos += len(b)
    tag_off[n] = pos

    # per (tenant, label): value -> [stream indices]; label -> any indices
    post: dict = {}
    for i, (a, p, _h, _l, tags) in enumerate(items):
        ti = tenant_idx_of[(a, p)]
        per = post.setdefault(ti, {})
        for label, value in parse_stream_tags(tags).items():
            lab = per.setdefault(label, {})
            lab.setdefault(value, []).append(i)

    arrays = {"t_idx": t_idx, "hi": hi, "lo": lo, "tag_off": tag_off,
              "tags_blob": b"".join(tag_parts)}
    labels_meta: dict = {}
    for ti, per in post.items():
        for label, values in per.items():
            any_arr = np.fromiter(
                sorted({i for ids in values.values() for i in ids}),
                dtype=np.uint32)
            _emit_label(arrays, labels_meta, ti, label, values, any_arr)

    _finish_snapshot(path, arrays, n, tenants, labels_meta, log_offset)


def compact_snapshot(path: str, snap, tail: dict,
                     log_offset: int) -> None:
    """One entry point for every compaction site: array-level merge when
    a snapshot exists, full write otherwise."""
    if snap is not None:
        merge_snapshot(path, snap, tail, log_offset)
    else:
        write_snapshot(path, dict(tail), log_offset)


def merge_snapshot(path: str, snap: "StreamSnapshot", tail: dict,
                   log_offset: int) -> None:
    """Array-level compaction: merge an existing snapshot with a tail map
    WITHOUT decoding the old rows into Python objects or re-parsing their
    tags — the mergeset file-to-file merge.  Old registry columns merge by
    one lexsort; old posting lists remap through the (monotonic) old→new
    index mapping; only TAIL tags are parsed."""
    n_old = snap.n
    t_items = sorted(
        ((sid.tenant.account_id, sid.tenant.project_id, sid.hi, sid.lo,
          tags) for sid, tags in tail.items()))
    n_tail = len(t_items)
    if n_tail == 0:
        # nothing to merge: rewrite with the new log offset only
        _finish_snapshot(path, dict(snap._arrays), n_old,
                         [(t.account_id, t.project_id)
                          for t in snap.tenants],
                         snap._labels_meta, log_offset)
        return

    # unified tenant table, SORTED by (account, project): rows are sorted
    # the same way, so t_idx stays monotonic — the invariant
    # StreamSnapshot._tenant_bounds (searchsorted) depends on
    old_tenant_keys = [(t.account_id, t.project_id) for t in snap.tenants]
    tenants = sorted(set(old_tenant_keys) |
                     {(a, p) for a, p, _h, _l, _t in t_items})
    tenant_idx_of = {t: i for i, t in enumerate(tenants)}

    # registry columns: concat old arrays with tail columns, one lexsort
    t_acct = np.fromiter((a for a, _p, _h, _l, _t in t_items),
                         dtype=np.int64, count=n_tail)
    t_proj = np.fromiter((p for _a, p, _h, _l, _t in t_items),
                         dtype=np.int64, count=n_tail)
    t_hi = np.fromiter((h for _a, _p, h, _l, _t in t_items),
                       dtype=np.uint64, count=n_tail)
    t_lo = np.fromiter((lw for _a, _p, _h, lw, _t in t_items),
                       dtype=np.uint64, count=n_tail)
    old_tenants = np.asarray([[t.account_id, t.project_id]
                              for t in snap.tenants], dtype=np.int64) \
        if snap.tenants else np.empty((0, 2), dtype=np.int64)
    o_acct = old_tenants[:, 0][snap.t_idx] if n_old else \
        np.empty(0, dtype=np.int64)
    o_proj = old_tenants[:, 1][snap.t_idx] if n_old else \
        np.empty(0, dtype=np.int64)
    acct = np.concatenate([o_acct, t_acct])
    proj = np.concatenate([o_proj, t_proj])
    hi = np.concatenate([snap.hi, t_hi])
    lo = np.concatenate([snap.lo, t_lo])
    perm = np.lexsort((lo, hi, proj, acct))
    n = n_old + n_tail
    # old/tail position -> new row index (monotonic within each source,
    # so sorted posting lists stay sorted after remapping)
    new_of = np.empty(n, dtype=np.int64)
    new_of[perm] = np.arange(n, dtype=np.int64)
    old_to_new = new_of[:n_old]
    tail_to_new = new_of[n_old:]

    old_lut = np.fromiter((tenant_idx_of[k] for k in old_tenant_keys),
                          dtype=np.uint32, count=len(old_tenant_keys))
    t_idx_all = np.concatenate([
        old_lut[snap.t_idx] if n_old else np.empty(0, dtype=np.uint32),
        np.fromiter((tenant_idx_of[(a, p)]
                     for a, p, _h, _l, _t in t_items),
                    dtype=np.uint32, count=n_tail)])[perm].astype(
                        np.uint32)

    # tags: slice table in merged order (old rows copy bytes, no decode)
    old_lens = np.diff(snap.tag_off.astype(np.int64))
    t_tag_bytes = [t.encode("utf-8") for _a, _p, _h, _l, t in t_items]
    lens_all = np.concatenate([
        old_lens, np.fromiter((len(b) for b in t_tag_bytes),
                              dtype=np.int64, count=n_tail)])[perm]
    tag_off = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum(lens_all, out=tag_off[1:])
    # one fancy gather instead of a per-row slice loop: concatenate the
    # source blobs, compute each merged row's source start, and index
    big_src = np.frombuffer(snap.tags_blob + b"".join(t_tag_bytes),
                            dtype=np.uint8)
    t_lens = np.fromiter((len(b) for b in t_tag_bytes), dtype=np.int64,
                         count=n_tail)
    t_starts = np.zeros(n_tail, dtype=np.int64)
    np.cumsum(t_lens[:-1], out=t_starts[1:])
    src_starts = np.concatenate([
        snap.tag_off[:n_old].astype(np.int64),
        t_starts + len(snap.tags_blob)])[perm]
    total_bytes = int(tag_off[n])
    assert total_bytes < 2 ** 31, "tags blob exceeds int32 gather range"
    out_off = tag_off[:n].astype(np.int64)
    gather = (np.repeat(src_starts - out_off, lens_all) +
              np.arange(total_bytes, dtype=np.int64)).astype(np.int32)
    tags_blob = big_src[gather].tobytes()

    arrays = {"t_idx": t_idx_all, "hi": hi[perm], "lo": lo[perm],
              "tag_off": tag_off, "tags_blob": tags_blob}

    # postings: old tables remap; tail postings (parsed here, tail only)
    # merge in per (tenant, label, value)
    tail_post: dict = {}
    for k, (a, p, _h, _l, tags) in enumerate(t_items):
        ti = tenant_idx_of[(a, p)]
        per = tail_post.setdefault(ti, {})
        for label, value in parse_stream_tags(tags).items():
            per.setdefault(label, {}).setdefault(value, []).append(
                int(tail_to_new[k]))

    labels_meta: dict = {}
    old_ti_of = {i: int(old_lut[i]) for i in range(len(old_tenant_keys))}
    seen: set = set()
    # old labels (remapped, merged with any tail postings on the same key)
    for old_ti_s, labels in snap._labels_meta.items():
        old_ti = int(old_ti_s)
        ti = old_ti_of[old_ti]
        for label in labels:
            seen.add((ti, label))
            base = f"p{old_ti}:{label}"
            vtab = snap._arrays[base + ":v"]
            counts = snap._arrays[base + ":c"]
            idx_blob = old_to_new[snap._arrays[base + ":i"]]
            any_arr = np.sort(old_to_new[snap._arrays[base + ":a"]])
            extra = tail_post.get(ti, {}).pop(label, None)
            if extra:
                any_arr = np.sort(np.concatenate(
                    [any_arr,
                     np.fromiter(sorted({i for ids in extra.values()
                                         for i in ids}),
                                 dtype=np.int64)]))
            if _merge_label_vectorized(arrays, labels_meta, ti, label,
                                       vtab, counts, idx_blob, extra,
                                       any_arr):
                continue
            # general path: few distinct values (dict-style labels)
            starts = np.zeros(len(counts) + 1, dtype=np.int64)
            np.cumsum(counts, out=starts[1:])
            values = {v.decode("utf-8"):
                      idx_blob[starts[k]:starts[k + 1]]
                      for k, v in enumerate(vtab)}
            if extra:
                for v, ids in extra.items():
                    ids = np.asarray(ids, dtype=np.int64)
                    values[v] = np.sort(np.concatenate(
                        [np.asarray(values.get(
                            v, np.empty(0, dtype=np.int64)),
                            dtype=np.int64), ids]))
            _emit_label(arrays, labels_meta, ti, label, values, any_arr)
    # labels that exist only in the tail
    for ti, per in tail_post.items():
        for label, vals in per.items():
            if (ti, label) in seen:
                continue
            values = {v: np.asarray(sorted(ids), dtype=np.int64)
                      for v, ids in vals.items()}
            any_arr = np.fromiter(
                sorted({i for ids in vals.values() for i in ids}),
                dtype=np.int64)
            _emit_label(arrays, labels_meta, ti, label, values, any_arr)

    _finish_snapshot(path, arrays, n, tenants, labels_meta, log_offset)


def _merge_label_vectorized(arrays: dict, labels_meta: dict, ti: int,
                            label: str, vtab, counts, idx_blob, extra,
                            any_arr) -> bool:
    """Pure-numpy merge for the high-cardinality shape where every value
    posts exactly ONE stream on both sides and no value repeats across
    sides (host-/id-like labels — exactly where a Python per-value loop
    hurts).  Returns False to use the general path otherwise."""
    if counts.size and int(counts.max()) > 1:
        return False
    if extra is not None and any(len(ids) != 1 for ids in extra.values()):
        return False
    if extra:
        skeys = sorted(extra, key=lambda v: v.encode("utf-8"))
        t_vals = np.array([v.encode("utf-8") for v in skeys], dtype="S")
        w = max(int(vtab.dtype.itemsize), int(t_vals.dtype.itemsize))
        t_ids = np.fromiter((extra[v][0] for v in skeys),
                            dtype=np.uint32, count=len(skeys))
        combined = np.concatenate([vtab.astype(f"S{w}"),
                                   t_vals.astype(f"S{w}")])
        ids_all = np.concatenate([idx_blob.astype(np.uint32), t_ids])
    else:
        combined = vtab
        ids_all = idx_blob.astype(np.uint32)
    order = np.argsort(combined, kind="stable")
    merged_vals = combined[order]
    if merged_vals.size > 1 and \
            bool((merged_vals[1:] == merged_vals[:-1]).any()):
        return False  # a value on both sides: counts would exceed 1
    base = f"p{ti}:{label}"
    arrays[base + ":v"] = merged_vals
    arrays[base + ":c"] = np.ones(merged_vals.size, dtype=np.uint32)
    arrays[base + ":i"] = ids_all[order]
    arrays[base + ":a"] = np.asarray(any_arr, dtype=np.uint32)
    labels_meta.setdefault(str(ti), {})[label] = {
        "w": int(merged_vals.dtype.itemsize) or 1}
    return True


def _emit_label(arrays: dict, labels_meta: dict, ti: int, label: str,
                values: dict, any_arr) -> None:
    """Serialize one (tenant, label) posting table into the arrays dict."""
    vkeys = sorted(values, key=lambda v: v.encode("utf-8"))
    vbytes = [v.encode("utf-8") for v in vkeys]
    w = max((len(b) for b in vbytes), default=1) or 1
    vtab = np.zeros((len(vkeys),), dtype=f"S{w}")
    counts = np.empty(len(vkeys), dtype=np.uint32)
    chunks = []
    for k, (vk, vb) in enumerate(zip(vkeys, vbytes)):
        vtab[k] = vb
        ids = values[vk]
        counts[k] = len(ids)
        chunks.append(np.asarray(ids, dtype=np.uint32))
    idx_blob = np.concatenate(chunks) if chunks else \
        np.empty(0, dtype=np.uint32)
    base = f"p{ti}:{label}"
    arrays[base + ":v"] = vtab
    arrays[base + ":c"] = counts
    arrays[base + ":i"] = idx_blob
    arrays[base + ":a"] = np.asarray(any_arr, dtype=np.uint32)
    labels_meta.setdefault(str(ti), {})[label] = {"w": w}


class _LabelPostings:
    """Lazy accessor for one (tenant, label)'s posting tables."""

    __slots__ = ("values", "counts", "idx_starts", "idx_blob", "any_idx",
                 "_decoded")

    def __init__(self, values, counts, idx_blob, any_idx):
        self.values = values                     # S-array, sorted
        self.counts = counts
        self.idx_starts = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=self.idx_starts[1:])
        self.idx_blob = idx_blob
        self.any_idx = any_idx
        self._decoded: list | None = None

    def lookup(self, value: str) -> np.ndarray:
        """Stream indices for label == value (empty if absent)."""
        vb = value.encode("utf-8")
        if len(vb) > self.values.dtype.itemsize:
            return np.empty(0, dtype=np.uint32)
        k = np.searchsorted(self.values, np.bytes_(vb))
        if k >= len(self.values) or self.values[k] != vb:
            return np.empty(0, dtype=np.uint32)
        return self.idx_blob[self.idx_starts[k]:self.idx_starts[k + 1]]

    def items(self):
        """(value_str, indices) pairs — regex filters walk all values."""
        if self._decoded is None:
            self._decoded = [v.decode("utf-8") for v in self.values]
        for k, v in enumerate(self._decoded):
            yield v, self.idx_blob[self.idx_starts[k]:
                                   self.idx_starts[k + 1]]


class StreamSnapshot:
    """Read-only view over one snapshot file."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            magic = f.read(len(SNAP_MAGIC))
            if magic != SNAP_MAGIC:
                raise ValueError("bad snapshot magic")
            raw = _zstd.decompress(f.read(), max_output_size=1 << 33)
        hlen = struct.unpack(">I", raw[:4])[0]
        hdr = json.loads(raw[4:4 + hlen])
        blob = memoryview(raw)[4 + hlen:]
        self.n: int = hdr["n"]
        self.log_offset: int = hdr["log_offset"]
        self.tenants = [TenantID(a, p) for a, p in hdr["tenants"]]
        self._tenant_idx = {t: i for i, t in enumerate(self.tenants)}
        arrays = {}
        for name, m in hdr["arrays"].items():
            seg = blob[m["off"]:m["off"] + m["len"]]
            arrays[name] = bytes(seg) if m["dtype"] == "bytes" else \
                np.frombuffer(seg, dtype=m["dtype"])
        self.t_idx = arrays["t_idx"]
        self.hi = arrays["hi"]
        self.lo = arrays["lo"]
        self.tag_off = arrays["tag_off"]
        self.tags_blob = arrays["tags_blob"]
        self._labels_meta = hdr["labels"]
        self._arrays = arrays
        self._postings_cache: dict = {}
        # rows are sorted by (tenant, hi, lo): per-tenant contiguous slices
        self._tenant_bounds = np.searchsorted(
            self.t_idx, np.arange(len(self.tenants) + 1, dtype=np.uint32))

    # ---- registry lookups ----
    def find(self, sid: StreamID) -> int:
        """Row index of sid, or -1."""
        ti = self._tenant_idx.get(sid.tenant)
        if ti is None:
            return -1
        s, e = int(self._tenant_bounds[ti]), int(self._tenant_bounds[ti + 1])
        h = np.uint64(sid.hi)
        i = s + int(np.searchsorted(self.hi[s:e], h))
        while i < e and self.hi[i] == h:
            if int(self.lo[i]) == sid.lo:
                return i
            if int(self.lo[i]) > sid.lo:
                return -1
            i += 1
        return -1

    def tags_at(self, i: int) -> str:
        a, b = int(self.tag_off[i]), int(self.tag_off[i + 1])
        return self.tags_blob[a:b].decode("utf-8")

    def stream_at(self, i: int) -> StreamID:
        return StreamID(self.tenants[int(self.t_idx[i])],
                        int(self.hi[i]), int(self.lo[i]))

    def streams_at(self, idxs) -> list:
        """Bulk StreamID materialization (tolist() beats per-element numpy
        indexing ~3x; only FINAL query results pay this)."""
        tis = self.t_idx[idxs].tolist()
        his = self.hi[idxs].tolist()
        los = self.lo[idxs].tolist()
        tenants = self.tenants
        return [StreamID(tenants[t], h, lw)
                for t, h, lw in zip(tis, his, los)]

    def tenant_range(self, tenant: TenantID) -> tuple[int, int]:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return (0, 0)
        return (int(self._tenant_bounds[ti]),
                int(self._tenant_bounds[ti + 1]))

    # ---- postings ----
    def label_postings(self, tenant: TenantID,
                       label: str) -> _LabelPostings | None:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return None
        key = (ti, label)
        got = self._postings_cache.get(key)
        if got is not None:
            return got
        if label not in self._labels_meta.get(str(ti), {}):
            return None
        base = f"p{ti}:{label}"
        lp = _LabelPostings(self._arrays[base + ":v"],
                            self._arrays[base + ":c"],
                            self._arrays[base + ":i"],
                            self._arrays[base + ":a"])
        self._postings_cache[key] = lp
        return lp
