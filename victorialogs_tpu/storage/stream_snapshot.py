"""Columnar stream-index snapshot files: the immutable levels of the
multi-level stream index (indexdb.py).

The reference backs its stream index with a mergeset LSM
(vendor/.../lib/mergeset/table.go: sorted immutable parts + background
merges + binary-searched lookups).  A snapshot file is one such part:
the tail of registrations flushes into a new file, and merge_snapshots()
is the k-way file-to-file background merge.

Layout (v2, `streams.snap.NNNNNN`): a JSON section directory followed by
the section payloads —
- registry sections (RAW, np.frombuffer over an mmap): streams sorted by
  (tenant, hi, lo) as u32 tenant_idx[], u64 hi[], u64 lo[], plus tag
  offsets.  Reopen is O(header); pages fault in on first touch, so RSS
  tracks what queries actually read (the mergeset part.go idea: mmapped
  parts, per-block decompression).
- a zstd tags-blob section (lazy: decompressed on first tags_at), and
- one zstd section per (tenant, label) posting group: a sorted
  fixed-width value table (searchsorted '=' lookups, linear decode only
  for regex filters), per-value posting slices of one u32 stream-index
  blob, and the label's "any" posting list.  Decompressed lazily on the
  first query touching that label, memoized per (label, value).

v1 files (single zstd frame, pre-round-5) still load via the legacy
eager path.

Crash safety: files are written tmp+fsync+rename and record the log byte
offset they cover; reopen loads the manifest's levels and replays only
the log tail past the contiguous-healthy coverage (indexdb._load_levels).
A torn file is discarded — the log is never truncated, so nothing is
lost.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from ..utils import zstd as _zstd
from .log_rows import StreamID, TenantID
from .stream_filter import parse_stream_tags

SNAP_MAGIC = b"VLSNAP1\n"       # legacy: whole file one zstd frame
SNAP2_MAGIC = b"VLSNAP2\n"      # sectioned: mmap registry, lazy labels

_REGISTRY_SECTIONS = ("t_idx", "hi", "lo", "tag_off")


def _finish_snapshot(path: str, arrays: dict, n: int, tenants: list,
                     labels_meta: dict, log_offset: int) -> None:
    """v2 writer: registry arrays land RAW (np.frombuffer over an mmap
    at open — reopen is O(header), pages fault in on demand), tags and
    each (tenant, label) posting group land as independent zstd
    sections decompressed lazily on first query.  This is what makes a
    10M-stream reopen sub-second and keeps RSS at touched-pages instead
    of whole-index (the mergeset part.go idea: mmapped part files,
    per-block decompression)."""
    payloads: list = []
    sections: dict = {}
    off = 0

    def add(name: str, data, dtype: str, comp: str) -> None:
        nonlocal off
        sections[name] = {"off": off, "len": len(data), "dtype": dtype,
                          "comp": comp}
        payloads.append(data)
        off += len(data)

    for name in _REGISTRY_SECTIONS:
        arr = np.ascontiguousarray(arrays[name])
        add(name, memoryview(arr).cast("B"), str(arr.dtype), "raw")
    add("tags_blob", _zstd.compress(arrays["tags_blob"], level=3),
        "bytes", "zstd")
    for ti_s, labels in labels_meta.items():
        for label in labels:
            base = f"p{ti_s}:{label}"
            v = np.ascontiguousarray(arrays[base + ":v"])
            c = np.ascontiguousarray(arrays[base + ":c"],
                                     dtype=np.uint32)
            i = np.ascontiguousarray(arrays[base + ":i"],
                                     dtype=np.uint32)
            a = np.ascontiguousarray(arrays[base + ":a"],
                                     dtype=np.uint32)
            blob = struct.pack("<IIQQ", v.size, v.dtype.itemsize or 1,
                               i.size, a.size) + \
                v.tobytes() + c.tobytes() + i.tobytes() + a.tobytes()
            add(base, _zstd.compress(blob, level=3), "label", "zstd")

    header = json.dumps({
        "n": n, "tenants": tenants, "sections": sections,
        "labels": labels_meta, "log_offset": log_offset,
    }, separators=(",", ":")).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SNAP2_MAGIC)
        f.write(struct.pack(">I", len(header)))
        f.write(header)
        for data in payloads:
            f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_snapshot(path: str, streams: dict, log_offset: int) -> None:
    """streams: StreamID -> tags_str (any order); atomic tmp+rename."""
    items = sorted(
        ((sid.tenant.account_id, sid.tenant.project_id, sid.hi, sid.lo,
          tags) for sid, tags in streams.items()))
    n = len(items)
    tenants: list[tuple[int, int]] = []
    tenant_idx_of: dict[tuple[int, int], int] = {}
    t_idx = np.empty(n, dtype=np.uint32)
    hi = np.empty(n, dtype=np.uint64)
    lo = np.empty(n, dtype=np.uint64)
    tag_off = np.empty(n + 1, dtype=np.uint64)
    tag_parts = []
    pos = 0
    for i, (a, p, h, lw, tags) in enumerate(items):
        key = (a, p)
        ti = tenant_idx_of.get(key)
        if ti is None:
            ti = tenant_idx_of[key] = len(tenants)
            tenants.append(key)
        t_idx[i] = ti
        hi[i] = h
        lo[i] = lw
        tag_off[i] = pos
        b = tags.encode("utf-8")
        tag_parts.append(b)
        pos += len(b)
    tag_off[n] = pos

    # per (tenant, label): value -> [stream indices]; label -> any indices
    post: dict = {}
    for i, (a, p, _h, _l, tags) in enumerate(items):
        ti = tenant_idx_of[(a, p)]
        per = post.setdefault(ti, {})
        for label, value in parse_stream_tags(tags).items():
            lab = per.setdefault(label, {})
            lab.setdefault(value, []).append(i)

    arrays = {"t_idx": t_idx, "hi": hi, "lo": lo, "tag_off": tag_off,
              "tags_blob": b"".join(tag_parts)}
    labels_meta: dict = {}
    for ti, per in post.items():
        for label, values in per.items():
            any_arr = np.fromiter(
                sorted({i for ids in values.values() for i in ids}),
                dtype=np.uint32)
            _emit_label(arrays, labels_meta, ti, label, values, any_arr)

    _finish_snapshot(path, arrays, n, tenants, labels_meta, log_offset)


def merge_snapshots(path: str, snaps: list["StreamSnapshot"],
                    log_offset: int) -> None:
    """k-way array-level merge of immutable snapshot files into one —
    the mergeset file-to-file merge (vendor/.../lib/mergeset/table.go
    background merges).  No row is ever decoded into Python objects:
    registry columns merge by one lexsort over the concatenated arrays,
    posting lists remap through the source-position→new-row mapping and
    regroup with a stable two-pass sort, tags copy via one byte gather.

    Duplicate StreamIDs across sources (possible after a crash-replay
    overlap) collapse onto one row; their postings converge on the kept
    row and dedupe."""
    n_srcs = [s.n for s in snaps]
    n_total = sum(n_srcs)
    base_of = np.zeros(len(snaps) + 1, dtype=np.int64)
    np.cumsum(np.asarray(n_srcs, dtype=np.int64), out=base_of[1:])

    # unified tenant table, sorted by (account, project)
    tenant_keys = sorted({(t.account_id, t.project_id)
                          for s in snaps for t in s.tenants})
    tenant_idx_of = {t: i for i, t in enumerate(tenant_keys)}

    def _src_cols(s):
        tn = np.asarray([[t.account_id, t.project_id] for t in s.tenants],
                        dtype=np.int64) if s.tenants else \
            np.empty((0, 2), dtype=np.int64)
        return tn[:, 0][s.t_idx], tn[:, 1][s.t_idx]

    acct = np.concatenate([_src_cols(s)[0] for s in snaps])
    proj = np.concatenate([_src_cols(s)[1] for s in snaps])
    hi = np.concatenate([s.hi for s in snaps])
    lo = np.concatenate([s.lo for s in snaps])
    perm = np.lexsort((lo, hi, proj, acct))

    # duplicate collapse: equal (acct,proj,hi,lo) runs share one new row
    sa, sp_, sh, sl = acct[perm], proj[perm], hi[perm], lo[perm]
    first = np.ones(n_total, dtype=bool)
    if n_total > 1:
        first[1:] = ~((sa[1:] == sa[:-1]) & (sp_[1:] == sp_[:-1]) &
                      (sh[1:] == sh[:-1]) & (sl[1:] == sl[:-1]))
    new_idx_sorted = np.cumsum(first) - 1          # sorted pos -> new row
    n = int(new_idx_sorted[-1]) + 1 if n_total else 0
    new_of = np.empty(n_total, dtype=np.int64)     # source pos -> new row
    new_of[perm] = new_idx_sorted

    keep_pos = perm[first]                         # source pos of kept rows
    t_idx_all = np.fromiter(
        (tenant_idx_of[(int(a), int(p))]
         for a, p in zip(sa[first], sp_[first])),
        dtype=np.uint32, count=n)

    # tags: gather kept rows' bytes from the concatenated source blobs
    blob_base = np.zeros(len(snaps) + 1, dtype=np.int64)
    np.cumsum(np.asarray([len(s.tags_blob) for s in snaps],
                         dtype=np.int64), out=blob_base[1:])
    src_tag_start = np.concatenate(
        [s.tag_off[:s.n].astype(np.int64) + blob_base[k]
         for k, s in enumerate(snaps)]) if n_total else \
        np.empty(0, dtype=np.int64)
    src_tag_len = np.concatenate(
        [np.diff(s.tag_off.astype(np.int64)) for s in snaps]) \
        if n_total else np.empty(0, dtype=np.int64)
    lens_kept = src_tag_len[keep_pos]
    tag_off = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum(lens_kept, out=tag_off[1:])
    total_bytes = int(tag_off[n])
    big_src = np.frombuffer(b"".join(s.tags_blob for s in snaps),
                            dtype=np.uint8)
    out_off = tag_off[:n].astype(np.int64)
    # chunked gather: an index entry per output byte costs 8x the blob;
    # bound the transient to ~8MB of payload (64MB of index) per step
    tags_out = np.empty(total_bytes, dtype=np.uint8)
    _CHUNK_BYTES = 8 << 20
    row = 0
    while row < n:
        hic = int(np.searchsorted(out_off,
                                  out_off[row] + _CHUNK_BYTES, "right"))
        hic = max(hic, row + 1)
        lens_c = lens_kept[row:hic]
        nb = int(lens_c.sum())
        if nb:
            dst0 = int(out_off[row])
            gather = (np.repeat(src_tag_start[keep_pos[row:hic]] -
                                (out_off[row:hic] - dst0), lens_c) +
                      np.arange(nb, dtype=np.int64))
            tags_out[dst0:dst0 + nb] = big_src[gather]
        row = hic
    tags_blob = tags_out.tobytes() if total_bytes else b""

    arrays = {"t_idx": t_idx_all, "hi": sh[first], "lo": sl[first],
              "tag_off": tag_off, "tags_blob": tags_blob}

    # postings: per (new tenant, label), gather every source table,
    # remap ids, regroup by value with a stable two-pass sort
    by_key: dict = {}            # (new_ti, label) -> [(vals_S, ids_i64)]
    for k, s in enumerate(snaps):
        old_keys = [(t.account_id, t.project_id) for t in s.tenants]
        for old_ti_s, labels in s._labels_meta.items():
            old_ti = int(old_ti_s)
            ti = tenant_idx_of[old_keys[old_ti]]
            for label in labels:
                vtab, counts, idx_blob, any_blob = \
                    s.label_arrays(old_ti, label)
                ids = new_of[base_of[k] + idx_blob.astype(np.int64)]
                vals = np.repeat(vtab, counts)
                any_ids = new_of[base_of[k] + any_blob.astype(np.int64)]
                by_key.setdefault((ti, label), []).append(
                    (vals, ids, any_ids))

    labels_meta: dict = {}
    for (ti, label), parts in by_key.items():
        w = max(int(v.dtype.itemsize) for v, _i, _a in parts) or 1
        vcat = np.concatenate([v.astype(f"S{w}") for v, _i, _a in parts])
        icat = np.concatenate([i for _v, i, _a in parts])
        # stable two-pass == lexsort by (value, id) without S-dtype keys
        o1 = np.argsort(icat, kind="stable")
        o2 = np.argsort(vcat[o1], kind="stable")
        order = o1[o2]
        sv, si = vcat[order], icat[order]
        if sv.size > 1:                       # drop (value,id) duplicates
            dup = (sv[1:] == sv[:-1]) & (si[1:] == si[:-1])
            if dup.any():
                keep = np.concatenate([[True], ~dup])
                sv, si = sv[keep], si[keep]
        # run-length by value -> vtab/counts/idx_blob
        if sv.size:
            starts = np.concatenate(
                [[True], sv[1:] != sv[:-1]]).nonzero()[0]
            vtab_new = sv[starts]
            counts_new = np.diff(
                np.concatenate([starts, [sv.size]])).astype(np.uint32)
        else:
            vtab_new = sv
            counts_new = np.empty(0, dtype=np.uint32)
        any_new = np.unique(np.concatenate([a for _v, _i, a in parts]))
        base = f"p{ti}:{label}"
        arrays[base + ":v"] = vtab_new
        arrays[base + ":c"] = counts_new
        arrays[base + ":i"] = si.astype(np.uint32)
        arrays[base + ":a"] = any_new.astype(np.uint32)
        labels_meta.setdefault(str(ti), {})[label] = {"w": w}

    _finish_snapshot(path, arrays, n, tenant_keys, labels_meta,
                     log_offset)


def _emit_label(arrays: dict, labels_meta: dict, ti: int, label: str,
                values: dict, any_arr) -> None:
    """Serialize one (tenant, label) posting table into the arrays dict."""
    vkeys = sorted(values, key=lambda v: v.encode("utf-8"))
    vbytes = [v.encode("utf-8") for v in vkeys]
    w = max((len(b) for b in vbytes), default=1) or 1
    vtab = np.zeros((len(vkeys),), dtype=f"S{w}")
    counts = np.empty(len(vkeys), dtype=np.uint32)
    chunks = []
    for k, (vk, vb) in enumerate(zip(vkeys, vbytes)):
        vtab[k] = vb
        ids = values[vk]
        counts[k] = len(ids)
        chunks.append(np.asarray(ids, dtype=np.uint32))
    idx_blob = np.concatenate(chunks) if chunks else \
        np.empty(0, dtype=np.uint32)
    base = f"p{ti}:{label}"
    arrays[base + ":v"] = vtab
    arrays[base + ":c"] = counts
    arrays[base + ":i"] = idx_blob
    arrays[base + ":a"] = np.asarray(any_arr, dtype=np.uint32)
    labels_meta.setdefault(str(ti), {})[label] = {"w": w}


class _LabelPostings:
    """Lazy accessor for one (tenant, label)'s posting tables."""

    __slots__ = ("values", "counts", "idx_starts", "idx_blob", "any_idx",
                 "_decoded")

    def __init__(self, values, counts, idx_blob, any_idx):
        self.values = values                     # S-array, sorted
        self.counts = counts
        self.idx_starts = np.zeros(len(counts) + 1, dtype=np.int64)
        np.cumsum(counts, out=self.idx_starts[1:])
        self.idx_blob = idx_blob
        self.any_idx = any_idx
        self._decoded: list | None = None

    def lookup(self, value: str) -> np.ndarray:
        """Stream indices for label == value (empty if absent)."""
        vb = value.encode("utf-8")
        if len(vb) > self.values.dtype.itemsize:
            return np.empty(0, dtype=np.uint32)
        k = np.searchsorted(self.values, np.bytes_(vb))
        if k >= len(self.values) or self.values[k] != vb:
            return np.empty(0, dtype=np.uint32)
        return self.idx_blob[self.idx_starts[k]:self.idx_starts[k + 1]]

    def items(self):
        """(value_str, indices) pairs — regex filters walk all values."""
        if self._decoded is None:
            self._decoded = [v.decode("utf-8") for v in self.values]
        for k, v in enumerate(self._decoded):
            yield v, self.idx_blob[self.idx_starts[k]:
                                   self.idx_starts[k + 1]]


class StreamSnapshot:
    """Read-only view over one snapshot file (v2 sectioned/mmap, or the
    legacy v1 single-frame format for files written before round 5)."""

    def __init__(self, path: str):
        f = open(path, "rb")
        magic = f.read(len(SNAP2_MAGIC))
        if magic == SNAP2_MAGIC:
            self._init_v2(f)
        elif magic == SNAP_MAGIC:
            with f:
                raw = _zstd.decompress(f.read(), max_output_size=1 << 33)
            self._init_v1(raw)
        else:
            f.close()
            raise ValueError("bad snapshot magic")
        self._tenant_idx = {t: i for i, t in enumerate(self.tenants)}
        self._postings_cache: dict = {}
        # rows are sorted by (tenant, hi, lo): per-tenant contiguous slices
        self._tenant_bounds = np.searchsorted(
            self.t_idx, np.arange(len(self.tenants) + 1, dtype=np.uint32))

    def _init_v2(self, f) -> None:
        import mmap as _mmap
        hlen = struct.unpack(">I", f.read(4))[0]
        hdr = json.loads(f.read(hlen))
        self._sections = hdr["sections"]
        need = len(SNAP2_MAGIC) + 4 + hlen + max(
            (m["off"] + m["len"] for m in self._sections.values()),
            default=0)
        size = os.fstat(f.fileno()).st_size
        if size < need:
            f.close()
            raise ValueError("truncated snapshot")
        self._mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        f.close()                      # the mmap keeps the file alive
        self._data0 = len(SNAP2_MAGIC) + 4 + hlen
        self.n = hdr["n"]
        self.log_offset = hdr["log_offset"]
        self.tenants = [TenantID(a, p) for a, p in hdr["tenants"]]
        self._labels_meta = hdr["labels"]
        self.t_idx = self._reg_array("t_idx")
        self.hi = self._reg_array("hi")
        self.lo = self._reg_array("lo")
        self.tag_off = self._reg_array("tag_off")
        self._tags_blob: bytes | None = None

    def _init_v1(self, raw: bytes) -> None:
        hlen = struct.unpack(">I", raw[:4])[0]
        hdr = json.loads(raw[4:4 + hlen])
        blob = memoryview(raw)[4 + hlen:]
        self.n = hdr["n"]
        self.log_offset = hdr["log_offset"]
        self.tenants = [TenantID(a, p) for a, p in hdr["tenants"]]
        arrays = {}
        for name, m in hdr["arrays"].items():
            seg = blob[m["off"]:m["off"] + m["len"]]
            arrays[name] = bytes(seg) if m["dtype"] == "bytes" else \
                np.frombuffer(seg, dtype=m["dtype"])
        self.t_idx = arrays["t_idx"]
        self.hi = arrays["hi"]
        self.lo = arrays["lo"]
        self.tag_off = arrays["tag_off"]
        self._tags_blob = arrays["tags_blob"]
        self._labels_meta = hdr["labels"]
        self._v1_arrays = arrays
        self._mm = None

    def _reg_array(self, name: str) -> np.ndarray:
        m = self._sections[name]
        dt = np.dtype(m["dtype"])
        return np.frombuffer(self._mm, dtype=dt,
                             count=m["len"] // dt.itemsize,
                             offset=self._data0 + m["off"])

    def _section_bytes(self, name: str) -> bytes:
        m = self._sections[name]
        start = self._data0 + m["off"]
        raw = self._mm[start:start + m["len"]]
        if m["comp"] == "zstd":
            return _zstd.decompress(raw, max_output_size=1 << 33)
        return raw

    @property
    def tags_blob(self) -> bytes:
        if self._tags_blob is None:
            self._tags_blob = self._section_bytes("tags_blob")
        return self._tags_blob

    def label_arrays(self, ti: int, label: str):
        """(vtab, counts, idx_blob, any) for one (tenant, label) — the
        raw posting tables, decoded lazily for v2 sections.  Used by
        label_postings and the k-way merge."""
        base = f"p{ti}:{label}"
        if self._mm is None:                      # v1: already in memory
            a = self._v1_arrays
            return (a[base + ":v"], a[base + ":c"], a[base + ":i"],
                    a[base + ":a"])
        blob = self._section_bytes(base)
        nv, w, ni, na = struct.unpack_from("<IIQQ", blob, 0)
        o = struct.calcsize("<IIQQ")
        v = np.frombuffer(blob, dtype=f"S{w}", count=nv, offset=o)
        o += nv * w
        c = np.frombuffer(blob, dtype=np.uint32, count=nv, offset=o)
        o += nv * 4
        i = np.frombuffer(blob, dtype=np.uint32, count=int(ni), offset=o)
        o += int(ni) * 4
        a = np.frombuffer(blob, dtype=np.uint32, count=int(na), offset=o)
        return v, c, i, a

    # ---- registry lookups ----
    def find(self, sid: StreamID) -> int:
        """Row index of sid, or -1."""
        ti = self._tenant_idx.get(sid.tenant)
        if ti is None:
            return -1
        s, e = int(self._tenant_bounds[ti]), int(self._tenant_bounds[ti + 1])
        h = np.uint64(sid.hi)
        i = s + int(np.searchsorted(self.hi[s:e], h))
        while i < e and self.hi[i] == h:
            if int(self.lo[i]) == sid.lo:
                return i
            if int(self.lo[i]) > sid.lo:
                return -1
            i += 1
        return -1

    def contains_batch(self, tenant: TenantID, hi_arr: np.ndarray,
                       lo_arr: np.ndarray) -> np.ndarray:
        """Vectorized membership for one tenant's (hi, lo) id batch.

        Registration dedupe calls this once per snapshot level instead of
        a Python find() per stream: the hi probe is one searchsorted pair;
        the per-id loop below only runs for ids whose 64-bit hi hash HAS a
        run in this snapshot (i.e. ids that are present, or ~n/2^64
        false candidates), so registering new streams stays loop-free."""
        out = np.zeros(hi_arr.size, dtype=bool)
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return out
        s, e = (int(self._tenant_bounds[ti]),
                int(self._tenant_bounds[ti + 1]))
        if s == e:
            return out
        seg_hi = self.hi[s:e]
        seg_lo = self.lo[s:e]
        h = hi_arr.astype(np.uint64, copy=False)
        left = np.searchsorted(seg_hi, h, side="left")
        right = np.searchsorted(seg_hi, h, side="right")
        for k in np.nonzero(right > left)[0].tolist():
            lw, r = int(left[k]), int(right[k])
            j = lw + int(np.searchsorted(seg_lo[lw:r], lo_arr[k]))
            if j < r and seg_lo[j] == lo_arr[k]:
                out[k] = True
        return out

    def tags_at(self, i: int) -> str:
        a, b = int(self.tag_off[i]), int(self.tag_off[i + 1])
        return self.tags_blob[a:b].decode("utf-8")

    def stream_at(self, i: int) -> StreamID:
        return StreamID(self.tenants[int(self.t_idx[i])],
                        int(self.hi[i]), int(self.lo[i]))

    def streams_at(self, idxs) -> list:
        """Bulk StreamID materialization (tolist() beats per-element numpy
        indexing ~3x; only FINAL query results pay this)."""
        tis = self.t_idx[idxs].tolist()
        his = self.hi[idxs].tolist()
        los = self.lo[idxs].tolist()
        tenants = self.tenants
        return [StreamID(tenants[t], h, lw)
                for t, h, lw in zip(tis, his, los)]

    def tenant_range(self, tenant: TenantID) -> tuple[int, int]:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return (0, 0)
        return (int(self._tenant_bounds[ti]),
                int(self._tenant_bounds[ti + 1]))

    # ---- postings ----
    def label_postings(self, tenant: TenantID,
                       label: str) -> _LabelPostings | None:
        ti = self._tenant_idx.get(tenant)
        if ti is None:
            return None
        key = (ti, label)
        got = self._postings_cache.get(key)
        if got is not None:
            return got
        if label not in self._labels_meta.get(str(ti), {}):
            return None
        lp = _LabelPostings(*self.label_arrays(ti, label))
        self._postings_cache[key] = lp
        return lp
