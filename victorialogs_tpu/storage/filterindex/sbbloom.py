"""Split-block bloom filters (v2 per-block layout).

Classic v1 filters (storage/bloom.py) spread a token's 6 probe bits
over the whole filter: probing means 6 scattered word loads per token
per block.  The split-block layout (Lang et al. arXiv:2101.01719, the
Parquet SBBF shape) first selects ONE 256-bit block per token, then
confines all 6 probe bits to it — a probe touches one cache line on
the host and is one contiguous 8-lane gather + AND on the device.

Derivations stay pure integer math on the token's xxhash64 so host and
device never drift:

- in-block bit positions reuse THE pinned splitmix64 probe stream
  (`bloom.bloom_probe_positions(h, 4)` — a 256-bit block is exactly a
  4-word classic filter), so the iteration contract pinned by
  tests/test_filterbank.py covers this layout too;
- block selection is a fastrange reduction of an independently salted
  splitmix64 mix, so it shares no bits with the in-block stream.

Parameters match v1's budget: 16 bits per distinct token, 6 probe
bits.  The padded-block loading variance costs a little false-positive
rate vs classic (measured bound pinned in tests/test_filterindex.py);
sealed parts buy it back many times over in probe shape.
"""

from __future__ import annotations

import numpy as np

from ..bloom import bloom_probe_positions
from ...utils.hashing import splitmix64_np

SB_BLOCK_BITS = 256
SB_LANES = 8                     # 256 bits as 8 uint32 lanes
SB_BITS_PER_TOKEN = 16           # same budget as the classic filters
SB_HASHES = 6
# block-select salt: decorrelates the fastrange selector from the
# in-block splitmix64 probe stream (both start from the same xxhash64)
_SB_SELECT_SALT = np.uint64(0xA076_1D64_78BD_642F)


def sb_num_blocks(ntokens: int) -> int:
    """256-bit blocks allotted to `ntokens` distinct tokens."""
    return max(1, (ntokens * SB_BITS_PER_TOKEN + SB_BLOCK_BITS - 1)
               // SB_BLOCK_BITS)


def sb_block_select(hashes: np.ndarray, m) -> np.ndarray:
    """Token -> 256-bit block index in [0, m) via fastrange.

    `m` may be a scalar (one filter) or an int array broadcast against
    `hashes` (batched probing across blocks of different sizes)."""
    r = splitmix64_np(hashes.astype(np.uint64) ^ _SB_SELECT_SALT) \
        >> np.uint64(32)
    return ((r * np.asarray(m, dtype=np.uint64)) >> np.uint64(32)) \
        .astype(np.int64)


def sb_bit_positions(hashes: np.ndarray) -> np.ndarray:
    """In-block bit positions -> uint64[T, 6] in [0, 256)."""
    return bloom_probe_positions(hashes.astype(np.uint64), 4)


def sb_build(hashes: np.ndarray) -> np.ndarray:
    """Build one split-block filter -> uint32 lanes [8*m].

    Zero tokens build the minimum all-zero block, exactly like
    bloom_build's 64-bit floor: any probe misses, so the block is
    (correctly) killable for every token."""
    m = sb_num_blocks(len(hashes))
    lanes = np.zeros(SB_LANES * m, dtype=np.uint32)
    if len(hashes) == 0:
        return lanes
    h = hashes.astype(np.uint64)
    bsel = sb_block_select(h, m)                       # int64[T]
    pos = sb_bit_positions(h)                          # uint64[T, 6]
    lane = bsel[:, None] * SB_LANES + (pos >> np.uint64(5)).astype(np.int64)
    bit = np.uint32(1) << (pos & np.uint64(31)).astype(np.uint32)
    np.bitwise_or.at(lanes, lane, bit)
    return lanes


def sb_token_masks(hashes: np.ndarray) -> np.ndarray:
    """Per-token 256-bit probe masks -> uint32[T, 8].

    Block-size independent (only the block SELECTION depends on m), so
    one mask table serves every block of a part and ships to the
    device once per query."""
    t = len(hashes)
    masks = np.zeros((t, SB_LANES), dtype=np.uint32)
    if t == 0:
        return masks
    pos = sb_bit_positions(hashes)
    rows = np.broadcast_to(np.arange(t, dtype=np.int64)[:, None],
                           pos.shape)
    bit = np.uint32(1) << (pos & np.uint64(31)).astype(np.uint32)
    np.bitwise_or.at(masks, (rows, (pos >> np.uint64(5)).astype(np.int64)),
                     bit)
    return masks


def sb_contains_all(lanes: np.ndarray, hashes: np.ndarray) -> bool:
    """Host oracle: True when every token's 6 bits are set in its
    selected block (possible false positives, never false negatives)."""
    if len(hashes) == 0:
        return True
    m = lanes.shape[0] // SB_LANES
    base = sb_block_select(hashes.astype(np.uint64), m) * SB_LANES
    masks = sb_token_masks(hashes)
    words = lanes[base[:, None] + np.arange(SB_LANES)]
    return bool(((words & masks) == masks).all())
