"""Token→block maplets: the exact per-part micro-index.

"Which blocks might contain token t" is the question the classic path
answers with B bloom probes (one per candidate block).  A sealed part
can answer it with ONE lookup: sort the part-column's distinct token
hashes once at build time and store, per token, the posting list of
block ids that contain it ("Time To Replace Your Filter" — the maplet
idea of returning a VALUE, not a bit, per key).  AND-path leaf pruning
becomes a binary search + posting intersection whose result is an
EXACT candidate block list — zero false positives at block
granularity (up to 64-bit token-hash collisions, the same assumption
every other filter layer already makes), which the EXPLAIN planner
prices directly.

Blocks that carry no token hashes for the column (missing column,
dict-encoded, bloom-less) can hide anything; they ride a `covered`
bitmap and are kept unconditionally, exactly like the classic path
keeps bloom-less blocks.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class Maplet:
    uhashes: np.ndarray      # uint64[U] sorted distinct token hashes
    offsets: np.ndarray      # int64[U+1] posting ranges into blocks
    blocks: np.ndarray       # int32[P] block ids, grouped per token
    covered: np.ndarray      # packbits bool[nblocks]: block has hashes
    nblocks: int

    def nbytes(self) -> int:
        return int(self.uhashes.nbytes + self.offsets.nbytes
                   + self.blocks.nbytes + self.covered.nbytes)

    def covered_mask(self, bis: np.ndarray) -> np.ndarray:
        byte = self.covered[bis >> 3]
        return (byte >> (np.uint8(7) - (bis & 7).astype(np.uint8))) & 1 != 0

    def all_covered(self) -> bool:
        n = self.nblocks
        full, rem = divmod(n, 8)
        if full and not (self.covered[:full] == 0xFF).all():
            return False
        if rem:
            want = np.uint8((0xFF << (8 - rem)) & 0xFF)
            return bool(self.covered[full] & want == want)
        return True

    def keep_mask(self, hashes: np.ndarray, bis=None) -> np.ndarray:
        """bool keep-mask over `bis` (or all blocks): True where the
        block may contain ALL tokens — exact for covered blocks, always
        True for uncovered ones.  Same contract as
        filterbank.bloom_keep_mask, strictly fewer survivors."""
        sel = np.arange(self.nblocks, dtype=np.int64) if bis is None \
            else np.asarray(list(bis), dtype=np.int64)
        if len(hashes) == 0:
            return np.ones(sel.shape[0], dtype=bool)
        t = len(hashes)
        cnt = np.zeros(self.nblocks, dtype=np.int32)
        pos = np.searchsorted(self.uhashes, hashes)
        u = self.uhashes.shape[0]
        for k in range(t):
            p = int(pos[k])
            if p >= u or self.uhashes[p] != hashes[k]:
                # token absent from every covered block: only the
                # uncovered blocks can still match
                cnt = None
                break
            cnt[self.blocks[self.offsets[p]:self.offsets[p + 1]]] += 1
        if cnt is None:
            return ~self.covered_mask(sel)
        return (cnt[sel] == t) | ~self.covered_mask(sel)


def maplet_build(per_block: list, nblocks: int) -> Maplet:
    """Build from [(block_idx, uint64 hashes or None)] — one entry per
    block that has token hashes; every other block is uncovered."""
    covered = np.zeros(nblocks, dtype=bool)
    hs = []
    bs = []
    for bi, h in per_block:
        if h is None:
            continue
        covered[bi] = True
        if len(h):
            hs.append(np.asarray(h, dtype=np.uint64))
            bs.append(np.full(len(h), bi, dtype=np.int32))
    if hs:
        all_h = np.concatenate(hs)
        all_b = np.concatenate(bs)
        order = np.argsort(all_h, kind="stable")
        sh, sb = all_h[order], all_b[order]
        uhashes, starts = np.unique(sh, return_index=True)
        offsets = np.concatenate(
            [starts.astype(np.int64), [sh.shape[0]]])
        blocks = sb
    else:
        uhashes = np.zeros(0, dtype=np.uint64)
        offsets = np.zeros(1, dtype=np.int64)
        blocks = np.zeros(0, dtype=np.int32)
    return Maplet(uhashes=uhashes, offsets=offsets, blocks=blocks,
                  covered=np.packbits(covered), nblocks=nblocks)
