"""Versioned sidecar persistence for the v2 filter index.

One file per part — `filterindex.bin`, written into the part directory
in the same `write_part` pass that seals it (so the atomic rename
publishes part and index together, and part GC's rmtree collects
both).  `blooms.bin` is untouched: it remains the mandatory fallback.

Layout (all integers little-endian):

    magic     8  b"VLFIDX2\\n"
    version   u32
    nblocks   u32   (must match the part; guards stale copies)
    hdrlen    u32   (JSON header byte length)
    crc32     u32   (zlib.crc32 over header + payload)
    header    JSON  (per-column array descriptors [offset, length])
    payload   raw arrays, each 8-byte aligned

The loader re-derives every array as a zero-copy numpy view over one
payload buffer after verifying magic, version, block count, header
shape and the checksum; ANY mismatch raises SidecarInvalid and the
caller falls back to the classic blooms.bin path — a corrupt or
truncated sidecar can only cost speed, never results.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass

import numpy as np

from .maplet import Maplet, maplet_build
from .sbbloom import SB_LANES, sb_build
from .xorfilter import XorFilter, xor_build

FILTERINDEX_FILENAME = "filterindex.bin"
MAGIC = b"VLFIDX2\n"
VERSION = 2


class SidecarInvalid(Exception):
    """Sidecar failed verification; classic path must serve."""


@dataclass
class ColumnArtifacts:
    """One column's three sealed-part artifacts (see package doc)."""
    nsb: np.ndarray              # int32[nblocks] sb blocks per block
    lanes: np.ndarray            # uint32[SB_LANES*sum(nsb)] concat
    xor: XorFilter | None        # None when not every block is covered
    maplet: Maplet

    def lane_offsets(self) -> np.ndarray:
        """int64[nblocks] lane start of each block's sb filter."""
        off = np.zeros(self.nsb.shape[0], dtype=np.int64)
        np.cumsum(self.nsb[:-1].astype(np.int64) * SB_LANES,
                  out=off[1:])
        return off

    def nbytes(self) -> int:
        n = int(self.nsb.nbytes + self.lanes.nbytes
                + self.maplet.nbytes())
        if self.xor is not None:
            n += self.xor.nbytes()
        return n


class SidecarBuilder:
    """Accumulates per-(block, column) token hashes during the part
    write, then builds all three artifacts per column."""

    def __init__(self):
        self._cols: dict[str, list] = {}

    def add(self, block_idx: int, name: str, hashes) -> None:
        """hashes: uint64 array of the block-column's distinct token
        hashes, or None when the column has no token coverage there
        (dict-encoded / bloom-less) — the block stays uncovered."""
        self._cols.setdefault(name, []).append((block_idx, hashes))

    def build(self, nblocks: int,
              pool=None) -> dict[str, ColumnArtifacts]:
        """Per-column artifact builds are independent (each reads only
        its own hash lists), so with `pool` they run concurrently —
        the DataDB's block-build pool at part-seal time.  Assembly
        order is the accumulation (dict) order either way, so the
        serialized sidecar bytes never depend on the pool."""
        names = list(self._cols)
        if pool is None:
            arts = [self._build_column(nm, nblocks) for nm in names]
        else:
            arts = [f.result() for f in
                    [pool.submit(self._build_column, nm, nblocks)
                     for nm in names]]
        return dict(zip(names, arts))

    def _build_column(self, name: str, nblocks: int) -> ColumnArtifacts:
        per_block = self._cols[name]
        nsb = np.zeros(nblocks, dtype=np.int32)
        lane_parts = []
        for bi, h in per_block:
            if h is None:
                continue
            lanes = sb_build(np.asarray(h, dtype=np.uint64))
            nsb[bi] = lanes.shape[0] // SB_LANES
            lane_parts.append((bi, lanes))
        lane_parts.sort(key=lambda t: t[0])
        lanes = np.concatenate([lp for _bi, lp in lane_parts]) \
            if lane_parts else np.zeros(0, dtype=np.uint32)
        mp = maplet_build(per_block, nblocks)
        xf = xor_build(mp.uhashes) if mp.all_covered() else None
        return ColumnArtifacts(nsb=nsb, lanes=lanes, xor=xf, maplet=mp)


def build_sidecar(builder: SidecarBuilder, nblocks: int, pool=None):
    """build + stats, no IO (the bench rides this directly)."""
    cols = builder.build(nblocks, pool=pool)
    nbytes = sum(c.nbytes() for c in cols.values())
    keys = sum(int(c.maplet.uhashes.shape[0]) for c in cols.values())
    agg_bits = sum(8 * c.xor.fingerprints.shape[0]
                   for c in cols.values() if c.xor is not None)
    agg_keys = sum(int(c.maplet.uhashes.shape[0])
                   for c in cols.values() if c.xor is not None)
    stats = {
        "cols": len(cols),
        "tokens": keys,
        "bytes": nbytes,
        "agg_bits_per_key": round(agg_bits / agg_keys, 2)
        if agg_keys else 0.0,
    }
    return cols, stats


# ---------------- serialization ----------------

def _pack(chunks: list, arr: np.ndarray, dtype: str):
    """Append `arr` (8-byte aligned) -> [offset, length] descriptor."""
    pos = sum(len(c) for c in chunks)
    pad = (-pos) % 8
    if pad:
        chunks.append(b"\0" * pad)
        pos += pad
    raw = np.ascontiguousarray(arr).astype(dtype, copy=False).tobytes()
    chunks.append(raw)
    return [pos, int(arr.shape[0])]


def write_sidecar(dir_path: str, cols: dict[str, ColumnArtifacts],
                  nblocks: int,
                  filename: str = FILTERINDEX_FILENAME) -> int:
    """Serialize into dir_path/<filename> -> bytes written.

    filename: the in-place REBUILD path (index._rebuild_sidecar) writes
    to a .tmp name first and os.replace()s it over the final name, so a
    crash mid-write can never leave a half-written file under the name
    the loader probes (the seal-time build needs no such step — the
    whole part dir publishes by one atomic rename)."""
    chunks: list[bytes] = []
    hdr_cols: dict = {}
    for name, c in cols.items():
        d = {
            "nsb": _pack(chunks, c.nsb, "<i4"),
            "sb": _pack(chunks, c.lanes, "<u4"),
            "mh": _pack(chunks, c.maplet.uhashes, "<u8"),
            "mo": _pack(chunks, c.maplet.offsets, "<i8"),
            "mb": _pack(chunks, c.maplet.blocks, "<i4"),
            "cov": _pack(chunks, c.maplet.covered, "<u1"),
        }
        if c.xor is not None:
            d["xor"] = {"seed": int(c.xor.seed),
                        "seglen": int(c.xor.seglen),
                        "fp": _pack(chunks, c.xor.fingerprints, "<u1")}
        hdr_cols[name] = d
    payload = b"".join(chunks)
    header = json.dumps({"cols": hdr_cols,
                         "payload_bytes": len(payload)},
                        separators=(",", ":")).encode("utf-8")
    crc = zlib.crc32(header + payload) & 0xFFFFFFFF
    import struct
    blob = (MAGIC
            + struct.pack("<III", VERSION, nblocks, len(header))
            + struct.pack("<I", crc)
            + header + payload)
    path = os.path.join(dir_path, filename)
    with open(path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    return len(blob)


def _view(payload: bytes, desc, dtype: str) -> np.ndarray:
    off, n = desc
    itemsize = np.dtype(dtype).itemsize
    end = off + n * itemsize
    if off < 0 or end > len(payload):
        raise SidecarInvalid(f"array [{off},{n}]x{dtype} out of range")
    return np.frombuffer(payload, dtype=dtype, count=n, offset=off)


def load_sidecar(dir_path: str, nblocks: int):
    """-> (cols dict, payload_nbytes); raises SidecarInvalid on any
    verification failure, FileNotFoundError when the part predates v2."""
    path = os.path.join(dir_path, FILTERINDEX_FILENAME)
    with open(path, "rb") as f:
        blob = f.read()
    import struct
    if len(blob) < len(MAGIC) + 16:
        raise SidecarInvalid("truncated header")
    if blob[:len(MAGIC)] != MAGIC:
        raise SidecarInvalid("bad magic")
    version, nb, hdrlen = struct.unpack_from("<III", blob, len(MAGIC))
    (crc,) = struct.unpack_from("<I", blob, len(MAGIC) + 12)
    if version != VERSION:
        raise SidecarInvalid(f"version {version}")
    if nb != nblocks:
        raise SidecarInvalid(f"nblocks {nb} != part {nblocks}")
    body = blob[len(MAGIC) + 16:]
    if hdrlen > len(body):
        raise SidecarInvalid("header past EOF")
    header, payload = body[:hdrlen], body[hdrlen:]
    if (zlib.crc32(header + payload) & 0xFFFFFFFF) != crc:
        raise SidecarInvalid("checksum mismatch")
    try:
        hdr = json.loads(header)
        if len(payload) != hdr["payload_bytes"]:
            raise SidecarInvalid("payload length mismatch")
        cols: dict[str, ColumnArtifacts] = {}
        for name, d in hdr["cols"].items():
            nsb = _view(payload, d["nsb"], "<i4")
            if nsb.shape[0] != nblocks:
                raise SidecarInvalid("nsb length")
            mp = Maplet(
                uhashes=_view(payload, d["mh"], "<u8"),
                offsets=_view(payload, d["mo"], "<i8"),
                blocks=_view(payload, d["mb"], "<i4"),
                covered=_view(payload, d["cov"], "<u1"),
                nblocks=nblocks,
            )
            if mp.offsets.shape[0] != mp.uhashes.shape[0] + 1 or \
                    (mp.offsets[-1:] > mp.blocks.shape[0]).any() or \
                    mp.covered.shape[0] != (nblocks + 7) // 8:
                raise SidecarInvalid("maplet shape")
            if mp.blocks.shape[0] and \
                    (int(mp.blocks.max()) >= nblocks
                     or int(mp.blocks.min()) < 0):
                raise SidecarInvalid("maplet block id out of range")
            xf = None
            if "xor" in d:
                x = d["xor"]
                fp = _view(payload, x["fp"], "<u1")
                if fp.shape[0] != 3 * int(x["seglen"]):
                    raise SidecarInvalid("xor shape")
                xf = XorFilter(seed=int(x["seed"]),
                               seglen=int(x["seglen"]),
                               fingerprints=fp)
            cols[name] = ColumnArtifacts(nsb=nsb,
                                         lanes=_view(payload, d["sb"],
                                                     "<u4"),
                                         xor=xf, maplet=mp)
        return cols, len(payload)
    except SidecarInvalid:
        raise
    except Exception as e:  # malformed JSON/desc shapes of any kind
        raise SidecarInvalid(f"malformed header: {e!r}") from e
