"""Sealed-part filter index v2.

Parts are immutable after merge, so their filter index can be built
ONCE — at seal time, in the datadb merge/flush path — and traded for
layouts a mutable index could not afford:

- **split-block bloom planes** (`sbbloom.py`, Lang et al.
  arXiv:2101.01719): every token's K probe bits confined to one
  256-bit block, so a probe is ONE contiguous 8-lane gather + AND
  instead of K scattered lane selects — the layout the device
  keep-mask (tpu/bloom_device.plane_keep_sb) consumes directly.
- **xor-filter part aggregates** (`xorfilter.py`, Graf & Lemire
  arXiv:1912.08258): ~9.9 bits/key build-once filters over the
  part-column's distinct tokens, replacing the Bloofi OR-folds for
  sealed parts — smaller and O(1)-faster whole-part kills.
- **token→block maplets** (`maplet.py`, "Time To Replace Your
  Filter"): a compact map from token hash to a posting range of block
  ids — "which blocks might match" becomes one binary search yielding
  an EXACT candidate block list the EXPLAIN planner can price, instead
  of B per-block probes.

All three persist as ONE versioned, checksummed sidecar
(`filterindex.bin`, `sidecar.py`) inside the part directory next to
`blooms.bin`; part GC (the merge's rmtree) removes it with the part.
The loader (`index.py`) verifies magic/version/checksum and falls back
to `blooms.bin` + the classic filterbank planes on ANY mismatch — a
corrupt sidecar can only lose speed, never correctness.
`VL_FILTER_INDEX=v1` pins the classic path (neither builds nor reads
sidecars).
"""

from __future__ import annotations

from .index import (PartFilterIndex, enabled, mode,  # noqa: F401
                    part_index, sb_plane_for_staging)
from .sidecar import (FILTERINDEX_FILENAME, SidecarBuilder,  # noqa: F401
                      build_sidecar, write_sidecar)
