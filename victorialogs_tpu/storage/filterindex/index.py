"""Sidecar loader + per-part v2 index cache and query entry points.

The index attaches lazily to the (immutable) part object on first
probe — the same attach idiom as storage/filterbank.FilterBank — and
its host bytes charge the SAME global budget as the classic bloom
planes (`VL_BLOOM_BANK_MAX_BYTES`), released by a weakref finalizer
when the part is garbage-collected after a merge.  There is no second
unbounded filter cache: a sidecar that does not fit the remaining
budget is declined (classic path serves, correctness unchanged).

Every failure mode — missing file, bad magic/version/checksum, block
count mismatch, budget exhaustion — degrades to `None`, which callers
read as "use blooms.bin + the classic filterbank planes".
"""

from __future__ import annotations

import os
import threading
import weakref

import numpy as np
from ... import config

from ...obs import events
from .sbbloom import SB_LANES, sb_block_select, sb_token_masks
from .sidecar import ColumnArtifacts, SidecarInvalid, load_sidecar


def mode() -> str:
    """`v2` (default) or `v1` (the classic-path kill switch)."""
    return "v1" if config.env("VL_FILTER_INDEX") == "v1" else "v2"


def enabled() -> bool:
    return mode() != "v1"


class PartFilterIndex:
    """One sealed part's loaded v2 artifacts, all columns."""

    def __init__(self, cols: dict[str, ColumnArtifacts], nblocks: int,
                 nbytes: int):
        self.cols = cols
        self.nblocks = nblocks
        self.nbytes = nbytes
        self._mu = threading.Lock()
        self._planes: dict = {}
        self._charged: list = [nbytes]

    # ---- maplet: exact block-level keep masks ----

    def has(self, field: str) -> bool:
        return field in self.cols

    def keep_mask(self, field: str, hashes: np.ndarray,
                  bis=None) -> np.ndarray:
        """Exact keep-mask over `bis` (or all blocks) — same contract
        as filterbank.bloom_keep_mask, strictly fewer survivors.  A
        field with no sidecar column has no token coverage anywhere in
        the part: every block keeps (identical to the classic path)."""
        c = self.cols.get(field)
        if c is None:
            n = self.nblocks if bis is None else len(bis)
            return np.ones(n, dtype=bool)
        return c.maplet.keep_mask(hashes, bis)

    # ---- xor filter: O(1) whole-part kills ----

    def covers(self, field: str) -> bool:
        """Every block of the part has token coverage for the field
        (the precondition for a part-level kill, exactly mirroring the
        classic aggregate's all_have)."""
        c = self.cols.get(field)
        return c is not None and c.xor is not None

    def xor_kill(self, field: str, hashes: np.ndarray) -> bool:
        """True when some required token is provably absent from every
        block of the part."""
        c = self.cols.get(field)
        if c is None or c.xor is None or len(hashes) == 0:
            return False
        return not bool(c.xor.contains(hashes).all())

    # ---- split-block plane: the device-probe layout ----

    def has_sb(self, field: str) -> bool:
        c = self.cols.get(field)
        return c is not None and bool(c.nsb.any())

    def sb_plane(self, field: str):
        """(plane uint32[B, SB_LANES*Mmax], nsb int32[B]) packed for
        the device gather, or None (no sb filters / over budget).
        Built lazily, memoized on the index, charged to the bank."""
        with self._mu:
            got = self._planes.get(field, _UNSET)
        if got is not _UNSET:
            return got
        built = self._build_plane(field)
        if built is not None:
            from ..filterbank import _bank_try_charge
            nbytes = int(built[0].nbytes)
            # vlint: allow-balance-unguarded-acquire(a WON charge joins self._charged below, whose _bank_release finalizer _load registered at index creation; the race loser releases inline right after)
            if not _bank_try_charge(nbytes):
                # transient budget pressure: decline WITHOUT memoizing
                # so the plane can land once charges free up at part GC
                events.emit("bloom_bank_evict", field=field,
                            nbytes=nbytes, part="#sb_plane")
                return None
        with self._mu:
            got = self._planes.setdefault(field, built)
            if got is built and built is not None:
                # the winner's charge is released by the part-GC
                # finalizer; a race loser releases it right below
                self._charged.append(nbytes)
        if got is not built and built is not None:
            from ..filterbank import _bank_release
            _bank_release([nbytes])            # lost the build race
        return got

    def _build_plane(self, field: str):
        c = self.cols.get(field)
        if c is None or not c.nsb.any():
            return None
        mmax = int(c.nsb.max())
        plane = np.zeros((self.nblocks, SB_LANES * mmax),
                         dtype=np.uint32)
        off = c.lane_offsets()
        for bi in np.nonzero(c.nsb)[0]:
            n = int(c.nsb[bi]) * SB_LANES
            plane[bi, :n] = c.lanes[off[bi]:off[bi] + n]
        return plane, np.ascontiguousarray(c.nsb, dtype=np.int32)

    def sb_probe_idx(self, field: str, hashes: np.ndarray) -> np.ndarray:
        """Per-(block, token) lane base -> int32[B, T]: the token's
        selected 256-bit block times SB_LANES, 0 where the block has no
        filter (kept via the nsb==0 term in the probe).  THE block
        selection is sb_block_select — the same derivation sb_build
        inserted with, so build and probe can never drift."""
        c = self.cols[field]
        sel = sb_block_select(hashes,
                              c.nsb.astype(np.uint64)[:, None])
        return (sel * SB_LANES).astype(np.int32)

    @staticmethod
    def sb_masks(hashes: np.ndarray) -> np.ndarray:
        return sb_token_masks(hashes)


_UNSET = object()
_attach_mu = threading.Lock()


def part_index(part) -> PartFilterIndex | None:
    """The part's loaded v2 index, or None (no sidecar / invalid /
    VL_FILTER_INDEX=v1 / in-memory part / over budget).  The outcome
    is cached on the part — one sidecar read per part lifetime.

    The global mutex only mints the PER-PART lock; the sidecar read
    (and the optional in-place rebuild, which re-reads every bloom
    column) runs under the part's own lock so concurrent queries
    attaching DIFFERENT parts never serialize behind each other's
    disk IO — only same-part racers wait, which is exactly what keeps
    the bank charge in _load single-shot."""
    if not enabled():
        return None
    got = getattr(part, "_filter_index", _UNSET)
    if got is not _UNSET:
        return got or None
    path = getattr(part, "path", None)
    if path is None:
        part._filter_index = False        # unsealed in-memory part
        return None
    with _attach_mu:
        mu = getattr(part, "_filter_index_mu", None)
        if mu is None:
            mu = part._filter_index_mu = threading.Lock()
    with mu:
        got = getattr(part, "_filter_index", _UNSET)
        if got is not _UNSET:
            return got or None
        fi = _load(part, path)
        if fi is _DECLINED:
            # transient budget pressure: no memo — the sidecar can
            # still load on a later probe once part GC frees charges
            return None
        part._filter_index = fi if fi is not None else False
    return fi


_DECLINED = object()


def _load(part, path: str):
    """PartFilterIndex | None (permanent: missing/invalid sidecar) |
    _DECLINED (transient: over the bank budget right now)."""
    from ..filterbank import _bank_release, _bank_try_charge
    try:
        cols, nbytes = load_sidecar(path, part.num_blocks)
    except FileNotFoundError:
        # pre-v2 part (sealed before the filter index existed).
        # VL_FILTER_INDEX_REBUILD=1 rebuilds the sidecar IN PLACE from
        # blooms.bin + columns right here at part-open time — the
        # deterministic tokenizer recomputes exactly the hash sets the
        # blooms were built from (the merge pass-through discipline),
        # so long-lived deployments get maplet/xor/split-block pruning
        # without waiting for a merge to reseal the part.  Off by
        # default: the rebuild reads every bloom-covered column once.
        if not config.env_flag("VL_FILTER_INDEX_REBUILD"):
            return None                   # classic path serves
        if not _rebuild_sidecar(part, path):
            return None
        try:
            cols, nbytes = load_sidecar(path, part.num_blocks)
        except (FileNotFoundError, SidecarInvalid, OSError):
            return None
    except (SidecarInvalid, OSError) as e:
        events.emit("filter_index_fallback",
                    part=str(getattr(part, "uid", "?")),
                    reason=str(e))
        return None
    if not _bank_try_charge(nbytes):
        events.emit("bloom_bank_evict", field="#filterindex",
                    nbytes=nbytes,
                    part=str(getattr(part, "uid", "?")))
        return _DECLINED
    fi = PartFilterIndex(cols, part.num_blocks, nbytes)
    weakref.finalize(fi, _bank_release, fi._charged)
    from ..filterbank import _bank_track
    _bank_track(fi)
    return fi


def _rebuild_sidecar(part, path: str) -> bool:
    """Build + persist filterindex.bin for a sealed pre-v2 part, in
    place, from its published blooms.bin + column payloads.

    Runs under _attach_mu (one rebuild at a time, once per part
    lifetime); the file lands via write-to-.tmp + os.replace so a crash
    mid-write can never leave a half-written sidecar under the probed
    name (and the crc check would reject one anyway).  Advisory like
    the seal-time build: any failure journals filter_index_build_failed
    and the classic path keeps serving."""
    import time as _time
    from ..block import column_token_hashes
    from .sidecar import (FILTERINDEX_FILENAME, SidecarBuilder,
                          build_sidecar, write_sidecar)
    t0 = _time.perf_counter()
    try:
        builder = SidecarBuilder()
        covered = 0
        for bi in range(part.num_blocks):
            nrows = part.block_rows(bi)
            for name in part.block_col_names(bi):
                ch = part.block_column_meta(bi, name)
                if ch is None or ch.get("b") is None:
                    continue          # no bloom => no token coverage
                col = part.block_column(bi, name)
                h = column_token_hashes(col, nrows)
                if h is None:
                    continue
                builder.add(bi, name, h)
                covered += 1
        if not covered:
            return False              # nothing bloom-covered to index
        cols, stats = build_sidecar(builder, part.num_blocks)
        tmp = FILTERINDEX_FILENAME + ".tmp"
        stats["file_bytes"] = write_sidecar(path, cols,
                                            part.num_blocks,
                                            filename=tmp)
        os.replace(os.path.join(path, tmp),
                   os.path.join(path, FILTERINDEX_FILENAME))
    # vlint: allow-broad-except(rebuild is advisory, classic path serves)
    except Exception as e:
        events.emit("filter_index_build_failed",
                    part=str(getattr(part, "uid", "?")),
                    reason=repr(e), rebuilt=True)
        return False
    from ...obs import hist as _hist
    stats["build_s"] = round(_time.perf_counter() - t0, 6)
    _hist.FILTER_INDEX_BUILD.observe(stats["build_s"])
    events.emit("filter_index_built",
                part=os.path.basename(path), rebuilt=True, **stats)
    return True


def sb_plane_for_staging(part, field: str):
    """(plane, nsb) for tpu/bloom_device.stage_sb_plane, or None."""
    fi = part_index(part)
    if fi is None:
        return None
    return fi.sb_plane(field)
