"""Xor filters: build-once part-level aggregates (~9.9 bits/key).

Graf & Lemire (arXiv:1912.08258): a 3-wise xor construction over
c = 32 + ceil(1.23*n) 8-bit fingerprint slots answers membership with
one xor of three slot loads, at ~0.62x the classic filters' 16
bits/key and a fixed ~2^-8 false-positive rate — strictly better than
the Bloofi OR-folds it replaces for sealed parts, whose fp rate grows
with every block folded in.  The catch is the build: peeling can fail
(rarely) and costs O(n) — exactly the trade a part that never mutates
again can afford, and one a mutable filter cannot.

The peel here is round-vectorized numpy rather than the classic
per-key stack: each round finds ALL degree-1 slots at once, records
(key, slot), and removes the keys.  Assignment replays the rounds in
reverse; within one round every peeled key's OTHER two slots were
peeled in strictly later rounds (a same-round sibling slot would have
had degree >= 2), so each round assigns as one vectorized gather/xor.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ...utils.hashing import splitmix64_np

_MAX_TRIES = 16
FINGERPRINT_BITS = 8


# vlint: allow-canonical-helper(the 3-slot Graf-Lemire fastrange IS defined here, over per-segment seglen with a reseedable xor — not a copy of sb_block_select's salted whole-plane reduction)
def _slots_and_fp(hashes: np.ndarray, seed: int, seglen: int):
    """Three fastrange slot indexes + the 8-bit fingerprint, all pure
    integer math on (hash, seed) so probes re-derive them from the
    sidecar's stored seed."""
    z = splitmix64_np(hashes.astype(np.uint64) ^ np.uint64(seed))
    z2 = splitmix64_np(z)
    sl = np.uint64(seglen)
    h0 = (((z & np.uint64(0xFFFFFFFF)) * sl) >> np.uint64(32))
    h1 = (((z >> np.uint64(32)) * sl) >> np.uint64(32)) + sl
    h2 = (((z2 & np.uint64(0xFFFFFFFF)) * sl) >> np.uint64(32)) \
        + np.uint64(2) * sl
    fp = ((z2 >> np.uint64(56)) & np.uint64(0xFF)).astype(np.uint8)
    # fingerprint 0 would make an all-zero (empty) table claim
    # membership; remap it like the reference implementations
    fp = np.where(fp == 0, np.uint8(0xA5), fp)
    return h0.astype(np.int64), h1.astype(np.int64), h2.astype(np.int64), fp


@dataclass
class XorFilter:
    seed: int
    seglen: int
    fingerprints: np.ndarray       # uint8[3*seglen]

    def contains(self, hashes: np.ndarray) -> np.ndarray:
        """bool[T]: no false negatives for built keys, fp ~= 2^-8."""
        if len(hashes) == 0:
            return np.ones(0, dtype=bool)
        h0, h1, h2, fp = _slots_and_fp(hashes, self.seed, self.seglen)
        f = self.fingerprints
        return (f[h0] ^ f[h1] ^ f[h2]) == fp

    def nbytes(self) -> int:
        return int(self.fingerprints.nbytes)

    def bits_per_key(self, nkeys: int) -> float:
        return 8.0 * self.fingerprints.shape[0] / max(1, nkeys)


def xor_build(hashes: np.ndarray) -> XorFilter | None:
    """Build an xor filter over DISTINCT uint64 hashes; None when the
    peel fails _MAX_TRIES seeds in a row (astronomically unlikely —
    the caller falls back to having no part aggregate)."""
    keys = np.unique(hashes.astype(np.uint64))
    n = len(keys)
    seglen = max(4, (int(np.ceil(1.23 * n)) + 32 + 2) // 3)
    cap = 3 * seglen
    for attempt in range(_MAX_TRIES):
        seed = (0x9E3779B9 * (attempt + 1)) & 0xFFFFFFFF
        if n == 0:
            return XorFilter(seed=seed, seglen=seglen,
                             fingerprints=np.zeros(cap, dtype=np.uint8))
        h0, h1, h2, _fp = _slots_and_fp(keys, seed, seglen)
        slots = np.stack([h0, h1, h2], axis=1)         # int64[n, 3]
        count = np.zeros(cap, dtype=np.int64)
        xorkey = np.zeros(cap, dtype=np.int64)         # xor of key ids
        flat = slots.reshape(-1)
        np.add.at(count, flat, 1)
        np.bitwise_xor.at(
            xorkey, flat,
            np.repeat(np.arange(n, dtype=np.int64), 3))
        alive = np.ones(n, dtype=bool)
        rounds: list[tuple[np.ndarray, np.ndarray]] = []
        remaining = n
        while remaining:
            single = np.nonzero(count == 1)[0]
            if single.shape[0] == 0:
                break                                   # cycle: reseed
            kid = xorkey[single]
            # one key may sit in several degree-1 slots: peel it once
            kid, first = np.unique(kid, return_index=True)
            peel_slots = single[first]
            live = alive[kid]
            kid, peel_slots = kid[live], peel_slots[live]
            if kid.shape[0] == 0:
                break
            alive[kid] = False
            remaining -= kid.shape[0]
            krows = slots[kid].reshape(-1)
            np.add.at(count, krows, -1)
            np.bitwise_xor.at(xorkey, krows, np.repeat(kid, 3))
            rounds.append((kid, peel_slots))
        if remaining:
            continue
        fps = np.zeros(cap, dtype=np.uint8)
        _, _, _, fp_all = _slots_and_fp(keys, seed, seglen)
        for kid, peel_slots in reversed(rounds):
            ks = slots[kid]                             # int64[r, 3]
            acc = fps[ks[:, 0]] ^ fps[ks[:, 1]] ^ fps[ks[:, 2]]
            # the peel slot itself is still 0 in fps, so acc is the
            # xor of the OTHER two; set it to close the equation
            fps[peel_slots] = fp_all[kid] ^ acc
        return XorFilter(seed=seed, seglen=seglen, fingerprints=fps)
    return None
