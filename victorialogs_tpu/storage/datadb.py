"""Per-partition LSM over columnar parts.

Three tiers like the reference datadb (lib/logstorage/datadb.go:76-82):
in-memory parts -> small file parts -> big file parts, with background
merging, a `parts.json` manifest atomically rewritten on every part-set change
(datadb.go:909-916), unreferenced part dirs removed at open (datadb.go:158-159)
and periodic in-memory flush (datadb.go:272-300).

Merging is a streaming k-way block merge (`merge_block_streams` below):
parts iterate block-at-a-time in (stream_id, min_ts) order and same-stream
runs coalesce column-wise without decoding to rows, the same shape as the
reference's blockStreamMerger (block_stream_merger.go).  Concurrency is one
lock plus a flusher thread — on TPU hosts the query path gets its
parallelism from the device, not from goroutine-per-CPU merges.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time

import numpy as np

from . import block_build
from .block import BlockData, build_blocks
from .part import Part, write_part
from .values_encoder import decode_values
from ..obs import events as _events
from ..obs import hist as _hist
from ..obs import ingestledger as _ingestledger


def _all_system_tenant(parts) -> bool:
    """True when every block in `parts` belongs to the self-telemetry
    system tenant — the flush/merge was triggered purely by journal
    ingest, so its event must be counted, not re-journaled (the
    recursion guard's storage half; early-exits on the first real
    row, which for any mixed workload is block 0)."""
    from ..obs.journal import SYSTEM_TENANT_ID
    saw_any = False
    for p in parts:
        nb = getattr(p, "num_blocks", 0)
        for i in range(nb):
            saw_any = True
            if p.block_stream_id(i).tenant != SYSTEM_TENANT_ID:
                return False
    return saw_any

DEFAULT_PARTS_TO_MERGE = 15          # reference datadb.go:33-45
MIN_MERGE_MULTIPLIER = 1.7
MAX_INMEMORY_PARTS = 8
BIG_PART_SIZE = 64 << 20             # compressed bytes; small->big promotion
PARTS_JSON = "parts.json"


class InmemoryPart:
    """A flushed-but-not-yet-durable part: blocks held decoded in memory."""

    def __init__(self, blocks: list[BlockData]):
        from .part import next_part_uid
        self.uid = next_part_uid()
        self.blocks = blocks
        self.num_blocks = len(blocks)
        self.num_rows = sum(b.num_rows for b in blocks)
        self.min_ts = min((b.min_ts for b in blocks), default=0)
        self.max_ts = max((b.max_ts for b in blocks), default=0)
        self.created_at = time.monotonic()
        self.path = None

    # ---- uniform block-access API (see part.Part) ----
    def candidate_blocks(self, min_ts, max_ts):
        for bi, b in enumerate(self.blocks):
            if b.min_ts <= max_ts and b.max_ts >= min_ts:
                yield bi

    def block_stream_id(self, i):
        return self.blocks[i].stream_id

    def block_tags(self, i):
        return self.blocks[i].stream_tags_str

    def block_rows(self, i):
        return self.blocks[i].num_rows

    def block_min_ts(self, i):
        return self.blocks[i].min_ts

    def block_max_ts(self, i):
        return self.blocks[i].max_ts

    def block_consts(self, i):
        return self.blocks[i].const_columns

    def block_col_names(self, i):
        return [c.name for c in self.blocks[i].columns]

    def block_column_meta(self, i, name):
        c = self.blocks[i].get_column(name)
        if c is None:
            return None
        meta = {"n": c.name, "t": c.vtype}
        if c.dict_values is not None:
            meta["dict"] = c.dict_values
        meta["min"] = c.min_val
        meta["max"] = c.max_val
        return meta

    def block_column_bloom(self, i, name):
        c = self.blocks[i].get_column(name)
        return c.bloom if c is not None else None

    def block_column(self, i, name):
        return self.blocks[i].get_column(name)

    def block_timestamps(self, i):
        return self.blocks[i].timestamps

    def read_block(self, i):
        return self.blocks[i]

    def iter_blocks(self):
        yield from self.blocks

    def close(self):
        pass


def _block_rows(blocks: list[BlockData]):
    """Decode blocks into per-row tuples (only used for the rare
    overlapping-range case in the streaming merger)."""
    for b in blocks:
        nrows = b.num_rows
        col_strs = [(c.name, c.to_strings(nrows)) for c in b.columns]
        consts = b.const_columns
        ts = b.timestamps.tolist()
        for ri in range(nrows):
            fields = [(n, vals[ri]) for n, vals in col_strs if vals[ri] != ""]
            fields += [(k, v) for k, v in consts]
            yield (b.stream_id, ts[ri], fields, b.stream_tags_str)


def _row_merge_blocks(blocks: list[BlockData]) -> list[BlockData]:
    """Row-level merge for same-stream blocks with overlapping time ranges."""
    rows = sorted(_block_rows(blocks), key=lambda r: (r[0], r[1]))
    sid = rows[0][0]
    ts = np.fromiter((r[1] for r in rows), dtype=np.int64, count=len(rows))
    return build_blocks(sid, ts, [r[2] for r in rows],
                        stream_tags_str=rows[0][3])


MERGE_TARGET_ROWS = 128 * 1024   # coalesce small same-stream blocks up to
COALESCE_MIN_ROWS = 64 * 1024    # blocks >= this pass through unchanged


def _block_columns(b: BlockData) -> dict[str, list[str]]:
    n = b.num_rows
    out = {c.name: c.to_strings(n) for c in b.columns}
    for k, v in b.const_columns:
        out[k] = [v] * n
    return out


def _coalesce_same_stream(blocks: list[BlockData]) -> list[BlockData]:
    """Columnar concat + re-encode of small same-stream adjacent blocks.

    No per-row tuples and no sort: ranges are already ordered, so columns
    concatenate directly (the streaming redesign of the reference's
    mustMergeBlockStreams — block_stream_merger.go)."""
    from .block import build_block_from_columns
    if len(blocks) == 1:
        return blocks
    names: dict[str, None] = {}
    for b in blocks:
        for c in b.columns:
            names.setdefault(c.name, None)
        for k, _v in b.const_columns:
            names.setdefault(k, None)
    cols: dict[str, list[str]] = {n: [] for n in names}
    for b in blocks:
        bc = _block_columns(b)
        n = b.num_rows
        for name in names:
            vals = bc.get(name)
            cols[name].extend(vals if vals is not None else [""] * n)
    ts = np.concatenate([b.timestamps for b in blocks])
    total = int(ts.shape[0])
    out = []
    for i in range(0, total, MERGE_TARGET_ROWS):
        j = min(i + MERGE_TARGET_ROWS, total)
        chunk = {n: v[i:j] for n, v in cols.items()}
        out.append(build_block_from_columns(
            blocks[0].stream_id, ts[i:j], chunk,
            stream_tags_str=blocks[0].stream_tags_str))
    return out


def merge_block_streams(parts_blocks):
    """Streaming k-way merge of per-part block iterators.

    Each input yields BlockData sorted by (stream_id, min_ts).  Blocks whose
    (stream, time) range doesn't overlap any other part's stream straight
    through — big blocks are emitted as-is, runs of small same-stream blocks
    are coalesced column-wise.  Only genuinely overlapping ranges pay a
    row-level merge.  Memory stays bounded by a handful of blocks
    (the reference streams via blockStreamReaders — datadb.go:466-602)."""
    import heapq

    iters = [iter(pb) for pb in parts_blocks]
    heap = []
    seq = 0
    for it in iters:
        b = next(it, None)
        if b is not None:
            heapq.heappush(heap, (b.stream_id, b.min_ts, seq, b, it))
            seq += 1

    pending: list[BlockData] = []   # small same-stream blocks to coalesce
    pending_rows = 0

    def flush_pending():
        nonlocal pending, pending_rows
        if not pending:
            return []
        out = _coalesce_same_stream(pending) if len(pending) > 1 \
            else [pending[0]]
        pending = []
        pending_rows = 0
        return out

    while heap:
        sid, _mt, _s, b, it = heapq.heappop(heap)
        nb = next(it, None)
        if nb is not None:
            heapq.heappush(heap, (nb.stream_id, nb.min_ts, seq, nb, it))
            seq += 1
        # gather overlapping same-stream blocks from other parts
        group = [b]
        gmax = b.max_ts
        while heap:
            sid2, mt2, _s2, b2, it2 = heap[0]
            if sid2 != sid or mt2 > gmax:
                break
            heapq.heappop(heap)
            group.append(b2)
            gmax = max(gmax, b2.max_ts)
            nb2 = next(it2, None)
            if nb2 is not None:
                heapq.heappush(
                    heap, (nb2.stream_id, nb2.min_ts, seq, nb2, it2))
                seq += 1
        if len(group) > 1:
            merged = _row_merge_blocks(group)
        else:
            merged = group
        for mb in merged:
            if pending and pending[0].stream_id != mb.stream_id:
                yield from flush_pending()
            if mb.num_rows >= COALESCE_MIN_ROWS:
                yield from flush_pending()
                yield mb
                continue
            if pending_rows + mb.num_rows > MERGE_TARGET_ROWS:
                yield from flush_pending()
            pending.append(mb)
            pending_rows += mb.num_rows
    yield from flush_pending()


def merge_blocks(parts_blocks: list[list[BlockData]]) -> list[BlockData]:
    """Merge blocks from several parts into a fresh sorted block list."""
    return list(merge_block_streams(parts_blocks))


class DataDB:
    def __init__(self, path: str, flush_interval: float = 5.0):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.flush_interval = flush_interval
        self._lock = threading.Lock()
        # serializes merge selection+execution so two threads can never pick
        # overlapping part sets (reference serializes via per-tier merge
        # worker channels — datadb.go:209-262)
        self._merge_lock = threading.Lock()
        self.inmemory_parts: list[InmemoryPart] = []
        # parts mid-flush: removed from inmemory_parts but not yet replaced
        # by their file part — must stay query-visible (the reference swaps
        # partWrappers atomically; see ADVICE r1)
        self.flushing_parts: list[InmemoryPart] = []
        self.small_parts: list[Part] = []
        self.big_parts: list[Part] = []
        self._next_part_id = 0
        self._stop = threading.Event()
        # block-build shard pool (VL_BLOCK_BUILD_THREADS): lazily spun
        # on the first parallel build, joined by close(); the flush and
        # merge part writers ride the same pool for per-column
        # compression + sidecar builds
        self.build_pool = block_build.BuildPool()
        self._open_existing()
        # ingest never merges inline: a flusher thread turns in-memory
        # parts into small file parts (woken early under buffer pressure),
        # and a merge worker compacts the small/big tiers in the
        # background (reference per-tier merge workers — datadb.go:209-262)
        self._flush_wake = threading.Event()
        self._buffer_drained = threading.Condition(self._lock)
        self._merge_wake = threading.Event()
        self._merge_backoff_until = 0.0
        self.merges_done = 0
        # all shared state above must exist before either thread runs
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._flusher.start()
        self._merge_worker = threading.Thread(target=self._merge_loop,
                                              daemon=True)
        self._merge_worker.start()

    # ---- open / recovery ----
    def _open_existing(self) -> None:
        manifest = os.path.join(self.path, PARTS_JSON)
        names: list[str] = []
        if os.path.exists(manifest):
            with open(manifest) as f:
                names = json.load(f)["parts"]
        referenced = set(names)
        for entry in os.listdir(self.path):
            full = os.path.join(self.path, entry)
            if entry == PARTS_JSON or not os.path.isdir(full):
                continue
            if entry not in referenced:
                # leftover from crash mid-merge/mid-write: drop it
                shutil.rmtree(full, ignore_errors=True)
        for name in names:
            p = Part(os.path.join(self.path, name))
            p.name = name
            (self.big_parts if p.meta["compressed_size"] >= BIG_PART_SIZE
             else self.small_parts).append(p)
            try:
                num = int(name.split("_")[-1], 16)
                self._next_part_id = max(self._next_part_id, num + 1)
            except ValueError:
                pass

    # vlint: allow-lock-blocking-call(manifest swap atomic with part swap)
    def _write_manifest_locked(self) -> None:
        names = [p.name for p in self.small_parts + self.big_parts]
        tmp = os.path.join(self.path, PARTS_JSON + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"parts": names}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, PARTS_JSON))

    def _new_part_name_locked(self) -> str:
        name = f"part_{self._next_part_id:016x}"
        self._next_part_id += 1
        return name

    # ---- write path ----
    def must_add_blocks(self, blocks: list[BlockData]) -> None:
        """Rows were already encoded into blocks on the CALLER's thread
        (blocks_from_log_rows) — concurrent ingest threads parallelize the
        CPU-heavy encode naturally (numpy/zstd release the GIL), which is
        this design's analogue of the reference's per-CPU rowsBuffer
        shards (datadb.go:667-747).  The append itself is a short locked
        op; the flusher is woken early under pressure, and ingest only
        BLOCKS (backpressure) when the buffer is far over its budget."""
        if not blocks:
            return
        with self._lock:
            self.inmemory_parts.append(InmemoryPart(blocks))
            n = len(self.inmemory_parts)
            if n > MAX_INMEMORY_PARTS:
                self._flush_wake.set()
            # hard backpressure: don't let an ingest burst outrun the
            # flusher unboundedly (reference blocks in addRows when the
            # part set explodes)
            while len(self.inmemory_parts) > 4 * MAX_INMEMORY_PARTS and \
                    not self._stop.is_set():
                self._flush_wake.set()
                self._buffer_drained.wait(timeout=1.0)

    def must_add_log_rows(self, lr) -> None:
        """Row-batch entry: build blocks (sharded on the build pool when
        VL_BLOCK_BUILD_THREADS > 1) and buffer them."""
        self.must_add_blocks(self._build_blocks_timed(
            lambda ex: block_build.build_log_rows_blocks(lr, pool=ex)))

    def must_add_columns(self, lc) -> None:
        """Columnar-batch entry (LogColumns, possibly arena-backed from
        the typed wire): the storage chokepoint's block build.  The
        build extent is the ledger's `build` hop (nested inside the
        caller's `store` hop) and feeds the
        vl_ingest_block_build_seconds histogram."""
        self.must_add_blocks(self._build_blocks_timed(
            lambda ex: block_build.build_columns_blocks(lc, pool=ex)))

    def _build_blocks_timed(self, build) -> list[BlockData]:
        t0 = time.perf_counter()
        with _ingestledger.hop("build"):
            blocks = build(self.build_pool.executor())
        _hist.INGEST_BLOCK_BUILD.observe(time.perf_counter() - t0)
        return blocks

    # ---- flush / merge ----
    def _flush_loop(self) -> None:
        while True:
            self._flush_wake.wait(timeout=min(self.flush_interval, 1.0))
            if self._stop.is_set():
                return
            woken = self._flush_wake.is_set()
            self._flush_wake.clear()
            with self._lock:
                oldest = min((p.created_at for p in self.inmemory_parts),
                             default=None)
            if oldest is None:
                continue
            if woken or time.monotonic() - oldest >= self.flush_interval:
                try:
                    self.flush_inmemory_parts()
                # vlint: allow-broad-except(flusher thread must survive)
                except Exception:  # pragma: no cover - keep flusher alive
                    pass

    def _merge_loop(self) -> None:
        """Bounded background merge worker: compacts the small tier (and
        the big tier when it accumulates) without ever stalling ingest or
        the flusher."""
        while True:
            self._merge_wake.wait(timeout=1.0)
            if self._stop.is_set():
                return
            self._merge_wake.clear()
            if time.monotonic() < self._merge_backoff_until:
                continue
            try:
                self._maybe_merge()
            # vlint: allow-broad-except(backoff keeps merge worker alive)
            except Exception:
                # ENOSPC and friends: back off instead of re-running the
                # same full k-way merge every second against a full disk
                self._merge_backoff_until = time.monotonic() + 30.0

    def flush_inmemory_parts(self) -> None:
        """Merge all in-memory parts into one small file part (durable)."""
        with self._lock:
            imps = self.inmemory_parts
            if not imps:
                return
            self.inmemory_parts = []
            # keep the flushing parts query-visible until the file part is
            # registered, then drop both in one locked swap
            self.flushing_parts.extend(imps)
        t0 = time.perf_counter()
        try:
            if len(imps) == 1:
                merged = imps[0].blocks
            else:
                merged = merge_block_streams([im.blocks for im in imps])
            with self._lock:
                name = self._new_part_name_locked()
            fi_stats = write_part(os.path.join(self.path, name), merged,
                                  pool=self.build_pool.executor())
            p = Part(os.path.join(self.path, name))
            p.name = name
            with self._lock:
                gone = set(id(x) for x in imps)
                self.flushing_parts = [x for x in self.flushing_parts
                                       if id(x) not in gone]
                self.small_parts.append(p)
                self._write_manifest_locked()
                self._buffer_drained.notify_all()
            # freshness: age of the OLDEST buffered row batch at the moment
            # it became durably queryable; system-tenant-only flushes
            # (journal self-ingest) are excluded so idle servers report none
            if not _all_system_tenant(imps):
                _hist.INGEST_FRESHNESS.observe(
                    time.monotonic()
                    - min(im.created_at for im in imps))
            # a flush of journal-only rows reports AS journal work
            # (suppressed+counted) so the journal's own ingest cannot
            # tick the storage into a perpetual flush-event loop; the
            # subscriber check keeps the tenant scan off the
            # journal-disabled path entirely
            if _events.subscriber_count():
                tenant = _events.SYSTEM_TENANT \
                    if _all_system_tenant(imps) else None
                _events.emit(
                    "storage_flush", tenant=tenant,
                    parts=len(imps), rows=p.num_rows, out_part=name,
                    duration_ms=round(
                        (time.perf_counter() - t0) * 1e3, 3))
                if fi_stats is not None:
                    _events.emit("filter_index_built", tenant=tenant,
                                 part=name, **fi_stats)
        except BaseException:
            # put the in-memory parts back so their rows stay visible
            with self._lock:
                gone = set(id(x) for x in imps)
                self.flushing_parts = [x for x in self.flushing_parts
                                       if id(x) not in gone]
                self.inmemory_parts.extend(imps)
                self._buffer_drained.notify_all()
            raise
        self._merge_wake.set()

    def _maybe_merge(self) -> None:
        """Merge small parts when there are too many (bin-pack equivalent);
        an overgrown big tier compacts the same way."""
        with self._merge_lock:
            with self._lock:
                if len(self.small_parts) >= DEFAULT_PARTS_TO_MERGE:
                    to_merge, big = list(self.small_parts), False
                elif len(self.big_parts) >= DEFAULT_PARTS_TO_MERGE:
                    to_merge, big = list(self.big_parts), True
                else:
                    return
            self._merge_parts(to_merge, big=big)

    def force_merge(self) -> None:
        """Merge ALL file parts into one big part (reference MustForceMerge)."""
        self.flush_inmemory_parts()
        with self._merge_lock:
            with self._lock:
                to_merge = list(self.small_parts) + list(self.big_parts)
            if len(to_merge) > 1:
                self._merge_parts(to_merge, big=True)

    # long I/O under _merge_lock is its purpose: it serializes merges
    # vlint: allow-lock-blocking-call(coarse merge serialization lock)
    def _merge_parts(self, to_merge: list[Part], big: bool) -> None:
        t0 = time.perf_counter()
        # attribute BEFORE the merge runs: afterwards the source parts'
        # dirs are gone (journal-triggered merges report suppressed —
        # the recursion guard's merge half)
        system_only = bool(_events.subscriber_count()) and \
            _all_system_tenant(to_merge)
        merged = self._merge_parts_timed(to_merge, big,
                                         system_only=system_only)
        # storage-side observability: merge wall time feeds the
        # vl_storage_merge_duration_seconds histogram on /metrics
        from ..obs import hist
        dt = time.perf_counter() - t0
        hist.MERGE_SECONDS.observe(dt)
        if merged:
            _events.emit(
                "storage_merge",
                tenant=_events.SYSTEM_TENANT if system_only else None,
                level="big" if big else "small", parts=len(to_merge),
                rows=sum(p.num_rows for p in to_merge),
                duration_ms=round(dt * 1e3, 3))

    # vlint: allow-lock-blocking-call(coarse merge serialization lock)
    def _merge_parts_timed(self, to_merge: list[Part], big: bool,
                           system_only: bool = False) -> bool:
        # disk-space reservation: skip the merge when the output could not
        # fit (reference reserves before merging — datadb.go:478-493)
        need = int(sum(p.meta.get("compressed_size", 0)
                       for p in to_merge) * 1.2) + (64 << 20)
        try:
            free = shutil.disk_usage(self.path).free
        except OSError:
            free = None
        if free is not None and free < need:
            return False  # not enough space: keep the source parts
        # streaming k-way merge: blocks are read lazily per part and flow
        # straight into the part writer — bounded memory, no row decode for
        # non-overlapping ranges
        def part_iter(p):
            return (p.read_block(i) for i in range(p.num_blocks))
        merged = merge_block_streams([part_iter(p) for p in to_merge])
        with self._lock:
            name = self._new_part_name_locked()
        out_path = os.path.join(self.path, name)
        try:
            fi_stats = write_part(out_path, merged, big=big,
                                  pool=self.build_pool.executor())
        except BaseException:
            # a failed write must not leave its .tmp dir eating the very
            # disk space the merge ran out of
            shutil.rmtree(out_path + ".tmp", ignore_errors=True)
            raise
        newp = Part(os.path.join(self.path, name))
        newp.name = name
        with self._lock:
            dropped = set(id(p) for p in to_merge)
            self.small_parts = [p for p in self.small_parts
                                if id(p) not in dropped]
            self.big_parts = [p for p in self.big_parts
                              if id(p) not in dropped]
            if newp.meta["compressed_size"] >= BIG_PART_SIZE or big:
                self.big_parts.append(newp)
            else:
                self.small_parts.append(newp)
            self._write_manifest_locked()
            self.merges_done += 1
        # do NOT close the merged-away parts: concurrent queries may hold them
        # via snapshot_parts().  Unlinking is safe — open fds and mmaps stay
        # readable on POSIX, and Python closes the files when the last snapshot
        # reference dies (the reference gets the same effect via refcounted
        # partWrappers — datadb.go:100-149).
        reclaimed = 0
        for p in to_merge:
            reclaimed += p.meta.get("compressed_size", 0)
            shutil.rmtree(p.path, ignore_errors=True)
        # merged-away part dirs unlinked (fds of concurrent snapshot
        # holders stay readable; bytes return to the OS when the last
        # reference dies)
        _events.emit(
            "part_gc",
            tenant=_events.SYSTEM_TENANT if system_only else None,
            parts=len(to_merge), reclaimed_bytes=reclaimed)
        if fi_stats is not None:
            _events.emit(
                "filter_index_built",
                tenant=_events.SYSTEM_TENANT if system_only else None,
                part=name, **fi_stats)
        return True

    # ---- read path ----
    def snapshot_parts(self) -> list:
        """Stable part list for one query (parts are immutable once listed)."""
        with self._lock:
            return list(self.inmemory_parts) + list(self.flushing_parts) + \
                   list(self.small_parts) + list(self.big_parts)

    # ---- stats / lifecycle ----
    def stats(self) -> dict:
        with self._lock:
            # flushing_parts included: a stalled flush is exactly the
            # staleness this gauge exists to surface
            oldest = min((p.created_at for p in self.inmemory_parts
                          + self.flushing_parts), default=None)
            return {
                "inmemory_parts": len(self.inmemory_parts)
                + len(self.flushing_parts),
                "small_parts": len(self.small_parts),
                "big_parts": len(self.big_parts),
                "inmemory_rows": sum(p.num_rows for p in self.inmemory_parts
                                     + self.flushing_parts),
                "file_rows": sum(p.num_rows
                                 for p in self.small_parts + self.big_parts),
                "small_rows": sum(p.num_rows for p in self.small_parts),
                "big_rows": sum(p.num_rows for p in self.big_parts),
                "compressed_size": sum(p.meta["compressed_size"]
                                       for p in self.small_parts
                                       + self.big_parts),
                "uncompressed_size": sum(p.meta["uncompressed_size"]
                                         for p in self.small_parts
                                         + self.big_parts),
                # merge/flush health: how many tier compactions the
                # merge worker has queued up, everything it has done,
                # and how stale the oldest not-yet-durable rows are
                "pending_merges":
                    int(len(self.small_parts) >= DEFAULT_PARTS_TO_MERGE)
                    + int(len(self.big_parts) >= DEFAULT_PARTS_TO_MERGE),
                "merges_done": self.merges_done,
                "flush_age_seconds":
                    0.0 if oldest is None
                    else time.monotonic() - oldest,
            }

    def close(self) -> None:
        self._stop.set()
        self._flush_wake.set()
        self._merge_wake.set()
        self._flusher.join(timeout=5)
        self._merge_worker.join(timeout=5)
        self.flush_inmemory_parts()
        # after the final flush: nothing can submit build/compress work
        # anymore, so join the shard pool (vlsan sweeps vl-block-build
        # workers whose owner closed)
        self.build_pool.close()
        with self._lock:
            for p in self.small_parts + self.big_parts:
                p.close()
