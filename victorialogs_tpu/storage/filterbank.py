"""Filter-index subsystem: packed per-part bloom planes + part aggregates.

Turns bloom pruning from an O(blocks) host Python loop into one dense
batched probe per (part, column), plus an O(1) part-level kill:

- **Bloom plane** (split-block layout, Lang et al. arXiv:2101.01719
  reshaped for whole-part probing): every block's bloom words for one
  column packed into a single zero-padded uint32 matrix `[B, 2*Wmax]`
  (uint64 words as 2 little-endian uint32 lanes — the same lane
  reinterpretation the device kernels use).  Probe positions are
  computed host-side ONCE PER DISTINCT FILTER SIZE with
  `bloom.bloom_probe_positions` and broadcast to per-block gather
  indices, so testing T tokens against B blocks is a single vectorized
  gather + bit-test instead of B Python calls.  The same
  (plane, idx, shift, nwords) arguments drive the device probe
  (tpu/bloom_device.py) unchanged.

- **Part aggregate** (Bloofi-style, arXiv:1501.01941): fixed-width
  OR-folds of the block filters, one fold per distinct filter size
  (probe positions of a size-w filter span only w words, so sizes must
  not share a fold).  Word i of a block filter folds into aggregate
  word ``i % width``, so a bit set by ANY block is set in its size's
  aggregate and the probe has no false negatives.  A token whose
  probes miss for EVERY distinct block-filter size present in the part
  is absent from every block — the whole part dies in O(1) before any
  block header is touched by the query.

Both are derived purely from the existing blooms.bin sidecar (no format
change) and cached on the part object (parts are immutable).
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass

import numpy as np

from .. import config
from ..obs import activity, events, hist, tracing
from ..utils.hashing import cached_token_hashes
from .bloom import (BLOOM_HASHES, bloom_contains_all,
                    bloom_probe_positions_multi)

# aggregate fold width cap, in uint64 words (4096 words = 32 KiB bits);
# small parts fold at their own max filter size instead
AGG_WORDS = 4096

# planes beyond this decline to the per-block path (a pathological part
# with huge per-block filters must not balloon host memory)
_MAX_PLANE_BYTES = config.env_int("VL_BLOOM_PLANE_MAX_BYTES")

# global budget for ALL host-resident planes: planes duplicate the
# mmap'd blooms.bin data in RAM, so a long-lived server querying many
# (part, column) pairs must stay bounded — past the budget, new columns
# take the per-block fallback (identical semantics, just slower) until
# parts (and their banks) are garbage-collected
_BANK_MAX_BYTES = config.env_int("VL_BLOOM_BANK_MAX_BYTES")
_bank_mu = threading.Lock()
_bank_bytes = 0
# every live charge list registered with a _bank_release finalizer —
# the vlsan runtime sweep proves _bank_bytes == sum of live charges
# (>= 0) after every test (tools/vlint/vlsan.py)
_bank_owners: "weakref.WeakSet" = weakref.WeakSet()


def _bank_track(owner) -> None:
    """Register an object whose ._charged list was handed to a
    _bank_release weakref.finalize (FilterBank, PartFilterIndex)."""
    _bank_owners.add(owner)


def bank_check_balanced() -> tuple[bool, str]:
    """Budget-accounting invariant for the vlsan sweep: the global
    byte total equals the sum of every live owner's charges and never
    goes negative (a double release would).  Callers retry once after
    gc.collect() — a finalizer may not have run yet."""
    with _bank_mu:
        used = _bank_bytes
    live = sum(sum(o._charged) for o in list(_bank_owners))
    ok = used == live and used >= 0
    return ok, f"bank_bytes={used} sum(live charges)={live}"


def _bank_try_charge(n: int) -> bool:
    global _bank_bytes
    with _bank_mu:
        if _bank_bytes + n > _BANK_MAX_BYTES:
            return False
        _bank_bytes += n
        return True


def _bank_release(charges: list) -> None:
    """weakref.finalize callback: a collected FilterBank returns its
    planes' bytes to the budget (charges is the bank's live list)."""
    global _bank_bytes
    with _bank_mu:
        _bank_bytes -= sum(charges)
        charges.clear()


def bank_stats() -> dict:
    """Occupancy of the global host bloom-plane budget, for /metrics
    (vl_tpu_bloom_bank_used_bytes / vl_tpu_bloom_bank_max_bytes)."""
    with _bank_mu:
        return {"used_bytes": _bank_bytes, "max_bytes": _BANK_MAX_BYTES}


@dataclass
class BloomPlane:
    """All (block, column) bloom filters of one part column, packed."""
    plane: np.ndarray              # uint32[B, 2*Wmax], zero-padded
    nwords: np.ndarray             # int32[B]; 0 = block has no bloom
    sizes: tuple                   # distinct nonzero word counts, sorted
    size_id: np.ndarray            # int32[B] index into sizes (0 if none)
    nbytes: int

    # single-slot memo: the same (leaf, part) pair probes with the same
    # hashes from the planner, the evaluator and the prefetcher.  One
    # (key, value) tuple, swapped atomically (GIL) — concurrent probers
    # may duplicate work but never see a key/value mismatch.
    _memo: tuple | None = None

    def probe_tables(self, hashes: np.ndarray
                     ) -> tuple[np.ndarray, np.ndarray]:
        """Per-size gather tables -> (idx, shift) int32[S, T*6].

        idx is the uint32-lane index of each probe bit inside a plane
        row (2*word + high-half), shift the bit position within the
        lane; both derived from bloom_probe_positions so the host and
        device probes share one position derivation.
        """
        key = hashes.tobytes()
        memo = self._memo
        if memo is not None and memo[0] == key:
            return memo[1]
        p = len(hashes) * BLOOM_HASHES
        pos = bloom_probe_positions_multi(hashes, self.sizes) \
            .reshape(len(self.sizes), p)
        idx = ((pos >> np.uint64(6)) * np.uint64(2)
               + ((pos >> np.uint64(5)) & np.uint64(1))).astype(np.int32)
        shift = (pos & np.uint64(31)).astype(np.int32)
        self._memo = (key, (idx, shift))
        return idx, shift

    def block_probe_args(self, hashes: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
        """(idx, shift) int32[B, T*6] — per-block gather arguments."""
        idx_s, shift_s = self.probe_tables(hashes)
        return idx_s[self.size_id], shift_s[self.size_id]

    def keep_mask(self, hashes: np.ndarray,
                  bis=None) -> np.ndarray:
        """bool keep-mask: True where the block may contain ALL tokens
        (or has no bloom).  bis: optional block-idx list restricting the
        probe (returned mask is aligned with bis)."""
        from ..tpu.bloom_device import probe_np
        if bis is None:
            if len(hashes) == 0:
                return np.ones(self.plane.shape[0], dtype=bool)
            idx, shift = self.block_probe_args(hashes)
            return probe_np(self.plane, idx, shift, self.nwords)
        sel = np.asarray(list(bis), dtype=np.int64)
        if len(hashes) == 0:
            return np.ones(sel.shape[0], dtype=bool)
        idx_s, shift_s = self.probe_tables(hashes)
        sid = self.size_id[sel]
        # gather ONLY the probed lanes (cost scales with T*6, not Wmax;
        # plane[sel] would copy whole rows first).  Bit-test semantics
        # are probe_np's, pinned by the differential tests.
        words = self.plane[sel[:, None], idx_s[sid]]
        bits = (words >> shift_s[sid].astype(np.uint32)) & np.uint32(1)
        return (bits != 0).all(axis=1) | (self.nwords[sel] == 0)

    def device_bytes(self) -> int:
        return self.nbytes


@dataclass
class AggregateFilter:
    """Fixed-width OR-folds of the part's block filters, one per
    distinct filter size, padded into one matrix so a probe is a
    single vectorized gather over every (size, token, probe) at once.

    Probe positions of a size-w filter only span w words, so folding
    different sizes together saturates immediately; folding WITHIN a
    size is exact up to the width cap (word i ORs into i % width), and
    same-size blocks are naturally few — block filter size tracks the
    block's distinct token count."""
    mat: np.ndarray                # uint64[S, Wcap] zero-padded folds
    widths: np.ndarray             # uint64[S] fold width per size
    sizes: tuple                   # distinct filter word counts (|| mat)
    all_have: bool                 # every block has a non-empty bloom

    # small result memo: parts are immutable and a query probes the
    # same (leaf, part) pairs from the serial walk, the pipeline
    # planner AND the explain pricing pass; a DICT (not a single slot)
    # because several AND-path leaves alternate probes on one field's
    # aggregate and would thrash a one-entry memo.  Bounded: cleared
    # wholesale past _MEMO_MAX (GIL-atomic dict ops, no lock needed)
    _memo: dict | None = None
    _MEMO_MAX = 32

    def may_contain_all(self, hashes: np.ndarray) -> bool:
        """False only when some token is PROVABLY absent from every
        block (=> a filter requiring all tokens matches nothing in the
        part).  Blocks without blooms can hide anything, so a part
        where any block lacks one is never killable."""
        if not self.all_have or len(hashes) == 0:
            return True
        key = hashes.tobytes()
        memo = self._memo
        if memo is None:
            memo = self._memo = {}
        got = memo.get(key)
        if got is not None:
            return got
        pos = bloom_probe_positions_multi(hashes, self.sizes)  # [S,T,6]
        wi = (pos >> np.uint64(6)) % self.widths[:, None, None]
        bit = (self.mat[np.arange(len(self.sizes))[:, None, None],
                        wi.astype(np.int64)]
               >> (pos & np.uint64(63))) & np.uint64(1)
        # a token is possible if SOME size's fold holds all its probes
        out = bool(bit.astype(bool).all(axis=2).any(axis=0).all())
        if len(memo) >= self._MEMO_MAX:
            memo.clear()
        memo[key] = out
        return out


class FilterBank:
    """Per-part cache of bloom planes and aggregate filters.

    Attached lazily to the part object (Part and InmemoryPart both
    expose the uniform block_column_bloom API); parts are immutable so
    entries never invalidate.  Thread-safe: the evaluator, the
    prefetcher and concurrent partition workers may probe one part at
    once — builds run outside the lock and the first insert wins.
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._planes: dict = {}
        self._aggs: dict = {}
        # plane byte charges against the global budget, released when
        # the bank (== its part) is garbage-collected
        self._charged: list = []
        weakref.finalize(self, _bank_release, self._charged)
        _bank_track(self)

    def plane(self, part, field: str) -> BloomPlane | None:
        with self._mu:
            got = self._planes.get(field, _MISSING)
        if got is not _MISSING:
            return got
        built = _build_plane(part, field)
        if built is not None and not _bank_try_charge(built.nbytes):
            # budget exhausted — the would-be plane is evicted before
            # it ever lands (per-block path instead).  Previously
            # invisible; now a journal event AND the decline counter.
            events.emit("bloom_bank_evict", field=field,
                        nbytes=built.nbytes,
                        part=str(getattr(part, "uid", "?")))
            built = None               # budget exhausted: per-block path
        with self._mu:
            got = self._planes.setdefault(field, built)
            if got is built and built is not None:
                self._charged.append(built.nbytes)
        if got is not built and built is not None:
            _bank_release([built.nbytes])  # lost the build race
        return got

    def cached_plane(self, field: str) -> "BloomPlane | None":
        """The plane if one was already built; never builds (the
        aggregate can fold from raw blooms directly, so a pure CPU-path
        query must not pay the plane's B x 2*Wmax host memory)."""
        with self._mu:
            got = self._planes.get(field, _MISSING)
        return None if got is _MISSING else got

    def aggregate(self, part, field: str) -> AggregateFilter | None:
        with self._mu:
            got = self._aggs.get(field, _MISSING)
        if got is not _MISSING:
            return got
        built = _build_aggregate(part, field, self.cached_plane(field))
        with self._mu:
            got = self._aggs.setdefault(field, built)
        return got

    def cached_aggregate(self, field: str) -> "AggregateFilter | None":
        with self._mu:
            got = self._aggs.get(field, _MISSING)
        return None if got is _MISSING else got


_MISSING = object()
_attach_mu = threading.Lock()


def filter_bank(part) -> FilterBank:
    """The part's FilterBank, attached on first use."""
    fb = getattr(part, "_filter_bank", None)
    if fb is None:
        with _attach_mu:
            fb = getattr(part, "_filter_bank", None)
            if fb is None:
                fb = FilterBank()
                part._filter_bank = fb
    return fb


def _build_plane(part, field: str) -> BloomPlane | None:
    """Pack every block's bloom words for `field` into one uint32 plane.

    None when no block has a bloom for the column (nothing to probe) or
    the padded plane would exceed the size cap (per-block fallback)."""
    nblocks = part.num_blocks
    words_by_block: list = [None] * nblocks
    nwords = np.zeros(nblocks, dtype=np.int32)
    wmax = 0
    for bi in range(nblocks):
        w = part.block_column_bloom(bi, field)
        if w is None or w.shape[0] == 0:
            continue
        words_by_block[bi] = w
        nwords[bi] = w.shape[0]
        if w.shape[0] > wmax:
            wmax = int(w.shape[0])
    if wmax == 0:
        return None
    if nblocks * wmax * 8 > _MAX_PLANE_BYTES:
        return None
    plane = np.zeros((nblocks, 2 * wmax), dtype=np.uint32)
    for bi, w in enumerate(words_by_block):
        if w is None:
            continue
        lanes = np.ascontiguousarray(w, dtype=np.uint64).view(np.uint32)
        plane[bi, :lanes.shape[0]] = lanes
    sizes = tuple(sorted(int(s) for s in np.unique(nwords[nwords > 0])))
    size_of = {s: i for i, s in enumerate(sizes)}
    size_id = np.zeros(nblocks, dtype=np.int32)
    for bi in range(nblocks):
        if nwords[bi]:
            size_id[bi] = size_of[int(nwords[bi])]
    return BloomPlane(plane=plane, nwords=nwords, sizes=sizes,
                      size_id=size_id, nbytes=plane.nbytes)


def _fold_into(agg: np.ndarray, words: np.ndarray) -> None:
    aw = agg.shape[0]
    for start in range(0, words.shape[0], aw):
        chunk = np.asarray(words[start:start + aw], dtype=np.uint64)
        agg[:chunk.shape[0]] |= chunk


def _pack_aggs(aggs: dict, all_have: bool) -> AggregateFilter:
    sizes = tuple(sorted(aggs))
    wcap = max(a.shape[0] for a in aggs.values())
    mat = np.zeros((len(sizes), wcap), dtype=np.uint64)
    widths = np.empty(len(sizes), dtype=np.uint64)
    for si, s in enumerate(sizes):
        a = aggs[s]
        mat[si, :a.shape[0]] = a
        widths[si] = a.shape[0]
    return AggregateFilter(mat=mat, widths=widths, sizes=sizes,
                           all_have=all_have)


def _build_aggregate(part, field: str,
                     plane: BloomPlane | None) -> AggregateFilter | None:
    """Per-size OR-folds of the block filters.

    Rides the packed plane when available (pure row reductions per size
    group); falls back to a direct per-block fold when the plane
    declined on size.  None when no block has a bloom for the column."""
    if plane is not None:
        aggs = {}
        for si, w in enumerate(plane.sizes):
            rows = plane.plane[(plane.size_id == si)
                               & (plane.nwords > 0)]
            col_or = np.bitwise_or.reduce(rows[:, :2 * w], axis=0)
            lo = col_or[0::2].astype(np.uint64)
            hi = col_or[1::2].astype(np.uint64)
            words = lo | (hi << np.uint64(32))          # uint64[w]
            agg = np.zeros(min(w, AGG_WORDS), dtype=np.uint64)
            _fold_into(agg, words)
            aggs[w] = agg
        return _pack_aggs(aggs, bool((plane.nwords > 0).all()))
    aggs = {}
    have = 0
    nblocks = part.num_blocks
    for bi in range(nblocks):
        w = part.block_column_bloom(bi, field)
        if w is None or w.shape[0] == 0:
            continue
        have += 1
        size = int(w.shape[0])
        agg = aggs.get(size)
        if agg is None:
            agg = aggs[size] = np.zeros(min(size, AGG_WORDS),
                                        dtype=np.uint64)
        _fold_into(agg, w)
    if not aggs:
        return None
    return _pack_aggs(aggs, have == nblocks)


# ---------------- query-path entry points ----------------

def bloom_keep_mask(part, field: str, hashes: np.ndarray,
                    bis=None, observe: bool = True) -> np.ndarray:
    """THE bloom kill-path: bool keep-mask over `bis` (or all blocks),
    True where the block may contain ALL tokens (or has no bloom).

    Rides the packed plane when the column has one; columns without a
    plane (no blooms anywhere, or past the size cap) fall back to a
    per-block probe with identical semantics — every caller sees one
    contract, so the evaluator, prefetcher and fused planner can never
    diverge on survivors.

    A COLD plane build reads every block's bloom (forcing all lazy
    header groups) and charges the bank budget, so it only pays when
    the probed candidate set covers a sizable fraction of the part —
    the same coverage gate the searcher applies to aggregate builds;
    narrow probes ride an already-built plane or the per-block loop.

    observe=False skips the prune-ratio histogram and trace counters:
    the prefetcher probes the same (part, field, bis) the evaluator
    will re-probe at dispatch — only the dispatch probe counts.

    Sealed parts with a valid v2 sidecar (storage/filterindex) answer
    from the token→block maplet instead: one lookup, an EXACT keep set
    (strictly fewer survivors than the probabilistic probe, never a
    false negative), and no host plane build at all.  Every caller
    still sees this one contract — VL_FILTER_INDEX=v1, a corrupt
    sidecar or an unsealed part land on the classic path below."""
    from .filterindex import part_index
    fi = part_index(part)
    if fi is not None:
        return _observe_keep(fi.keep_mask(field, hashes, bis), observe)
    fb = filter_bank(part)
    pl = fb.cached_plane(field)
    if pl is None and (bis is None
                       or len(bis) * 4 >= part.num_blocks):
        pl = fb.plane(part, field)
    if pl is not None:
        return _observe_keep(pl.keep_mask(hashes, bis), observe)
    idxs = list(bis) if bis is not None else list(range(part.num_blocks))
    keep = np.ones(len(idxs), dtype=bool)
    if len(hashes) == 0:
        return keep
    for k, bi in enumerate(idxs):
        w = part.block_column_bloom(bi, field)
        if w is not None and w.shape[0] and \
                not bloom_contains_all(w, hashes):
            keep[k] = False
    return _observe_keep(keep, observe)


def _observe_keep(keep: np.ndarray, observe: bool = True) -> np.ndarray:
    """Per-probe prune accounting: the kill fraction feeds the
    vl_tpu_bloom_prune_ratio histogram, and an active trace's ambient
    span gets blocks_probed_bloom / blocks_killed_bloom counters."""
    n = int(keep.shape[0])
    if n and observe:
        killed = n - int(keep.sum())
        hist.PRUNE_RATIO.observe(killed / n)
        sp = tracing.current_span()
        if sp.enabled:
            sp.add("blocks_probed_bloom", n)
            sp.add("blocks_killed_bloom", killed)
        if killed:
            # live-progress twin of the span counter: the active-query
            # registry record (no-op when the query isn't tracked)
            activity.current_activity().add("blocks_killed_bloom",
                                            killed)
    return keep


def aggregate_kill_leaf(part, leaves, build: bool = True):
    """The (field, tokens, owner_filter, artifact) leaf whose required
    tokens are provably absent from every block of the part, or None —
    the EXPLAIN plan's kill citation (obs/explain.py) and the predicate
    behind part_aggregate_prunes.  No trace/registry side effects: pure
    probe, so the pricing pass can call it without polluting the
    counters the execution walk will land.

    Sealed v2 parts probe the xor-filter aggregate first (artifact
    `xor_aggregate`: ~0.62x the bits/key and a fixed ~2^-8 fp rate, so
    it kills a superset of what the classic fold kills); classic parts
    use the Bloofi-style OR-folds (artifact `bloom_fold`)."""
    from .filterindex import part_index
    fi = part_index(part)
    fb = filter_bank(part) if build else \
        getattr(part, "_filter_bank", None)
    for field, tokens, f in leaves:
        if fi is not None:
            if fi.xor_kill(field, cached_token_hashes(f, tokens)):
                return field, tokens, f, "xor_aggregate"
            if fi.covers(field):
                # the xor aggregate is exact over the part's token set
                # (no false negatives): when it declines to kill, the
                # coarser classic fold cannot kill either
                continue
        if fb is None:
            continue
        agg = fb.aggregate(part, field) if build else \
            fb.cached_aggregate(field)
        if agg is not None and \
                not agg.may_contain_all(cached_token_hashes(f, tokens)):
            return field, tokens, f, "bloom_fold"
    return None


def part_aggregate_prunes(part, leaves, build: bool = True) -> bool:
    """O(1) part-level kill: True when some AND-path filter leaf's
    required tokens are provably absent from every block of the part.

    leaves: [(field, tokens, owner_filter)] from
    logsql.filters.iter_and_path_token_leaves — owner_filter carries the
    per-filter token-hash cache so tokens hash once per query.
    build=False probes only aggregates that already exist (a cold build
    reads every block's bloom, which a time-narrow query touching few
    candidate blocks should not pay — the caller gates on candidate
    coverage)."""
    killed = aggregate_kill_leaf(part, leaves, build=build)
    if killed is not None:
        field, _tokens, _f, artifact = killed
        sp = tracing.current_span()
        if sp.enabled:
            sp.add("parts_pruned_aggregate")
            sp.set("last_aggregate_prune_field", field)
            sp.set("last_aggregate_prune_artifact", artifact)
        activity.current_activity().add("parts_pruned")
        return True
    return False


def maplet_leaf_keep(fi, leaves, bis):
    """THE shared AND-path maplet core — both the execution pruning
    below and the EXPLAIN walk (obs/explain._maplet_exact) ride it, so
    the priced candidate set can never diverge from what execution
    dispatches.  Returns (keep bool[len(bis)] | None, killing_leaf |
    None): keep is None when no leaf had maplet coverage; killing_leaf
    is the first leaf whose candidates emptied."""
    keep = None
    for field, tokens, f in leaves:
        if not fi.has(field):
            continue
        km = fi.keep_mask(field, cached_token_hashes(f, tokens), bis)
        keep = km if keep is None else keep & km
        if not keep.any():
            return keep, (field, tokens, f)
    return keep, None


def maplet_prune_candidates(part, leaves, bis, observe: bool = True):
    """Exact AND-path block pruning from the sealed part's token→block
    maplets: ONE lookup per leaf yields the candidate block list, so
    blocks that cannot satisfy every AND-path token leaf drop out
    BEFORE any header/bloom/dispatch work.  Returns the pruned block-id
    list (possibly `bis` unchanged); classic parts (no v2 sidecar)
    return `bis` untouched — their pruning happens per-leaf in
    bloom_keep_mask.

    The dropped blocks are exactly those the per-leaf kill-path would
    have zeroed (the maplet is exact on token membership), so results
    are identical — this only moves the kill earlier and makes its
    size knowable to the EXPLAIN planner."""
    from .filterindex import part_index
    fi = part_index(part)
    if fi is None or not leaves or not bis:
        return bis
    keep, _kill_leaf = maplet_leaf_keep(fi, leaves, bis)
    if keep is None:
        return bis
    n = len(bis)
    killed = n - int(keep.sum())
    if observe:
        sp = tracing.current_span()
        if sp.enabled:
            sp.add("blocks_probed_maplet", n)
            sp.add("blocks_killed_maplet", killed)
        if killed:
            activity.current_activity().add("blocks_killed_maplet",
                                            killed)
    if not killed:
        return bis
    return [bi for bi, k in zip(bis, keep) if k]
