"""Sharded, arena-fed block build: the storage flush path's encoder.

PR 18 typed the ingest wire end-to-end and moved the profile: a storage
node decodes i1 frames ~4x faster than the format-independent block
build (values encoder + token blooms + filter-index sidecar) consumes
them, and the build cost is dominated by per-row Python string handling.
This module closes the gap from both ends:

- **columnar encode** (`ArenaColumn` + `encode_arena_column`): a decoded
  i1 value column stays ONE dense byte arena + offset/length tables all
  the way from `wire_ingest.decode_frame` to `BlockData`.  Const/dict
  detection and the numeric trial gates run vectorized over the arena,
  and a VT_STRING payload is gathered with one fancy index — no per-row
  Python string objects exist in between.  Every outcome is byte-exact
  with the row path's `encode_values` (the numeric trial cascade itself
  is SHARED — `values_encoder.try_typed_encoding`), and any input the
  vectorized gates can't prove (non-ASCII arenas never get here; NUL
  bytes fall through) takes the materialized-list path wholesale, so
  parity holds by construction.

- **cross-core sharding** (`BuildPool` + the builders' ``pool=``):
  block chunks are independent by construction — one (stream,
  size-bounded chunk) each — so they encode on a
  ``VL_BLOCK_BUILD_THREADS`` pool owned by the partition's `DataDB`
  (numpy, the native tokenizer and zstd all drop the GIL).  Tasks are
  collected in SUBMISSION order, so the block list — and every flushed
  part downstream — is byte-identical to the serial build at any
  thread count.  ``0``/``1`` threads = serial, no pool ever spun.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import deque

import numpy as np

from .. import config
from .block import (BlockData, _build_one_block, build_column_bloom,
                    build_blocks, chunk_end, row_cost_cum)
from .bloom import bloom_build
from .values_encoder import (MAX_DICT_BYTES, MAX_DICT_ENTRIES, VT_CONST,
                             VT_DICT, VT_STRING, EncodedColumn,
                             encode_values, try_typed_encoding)

# threads a freshly-created pool will spawn when the env knob is unset:
# the build is the storage chokepoint, so default to real parallelism,
# capped — a 128-core host should not give every per-day DataDB 128
# workers
_DEFAULT_THREAD_CAP = 8


def build_threads() -> int:
    """Resolved VL_BLOCK_BUILD_THREADS (<=1 means serial build)."""
    n = config.env_int("VL_BLOCK_BUILD_THREADS",
                       min(os.cpu_count() or 1, _DEFAULT_THREAD_CAP))
    return max(0, int(n))


def arena_build_enabled() -> bool:
    """VL_ARENA_BUILD kill switch: `0` keeps decode_frame materializing
    per-value strings (the pre-arena behavior, bit-identical output)."""
    return config.env_flag("VL_ARENA_BUILD")


class ArenaColumn:
    """One decoded i1 value column kept AS its wire arena.

    ASCII-only by construction (`decode_frame` builds one only when the
    decoded text length equals the raw byte length): byte offsets ==
    char offsets, so the chunker's char-length row costs equal byte
    lengths, numpy's S->U casts are exact, and slicing the decoded text
    is exact.  Behaves like a read-only list of str for the slow paths
    (split_by_day, multi-group streams, legacy re-encode) while the
    block build consumes raw/offs/lens directly."""

    __slots__ = ("raw", "u8", "offs", "lens", "text", "_mat")

    def __init__(self, raw: bytes, offs, lens, text: str):
        self.raw = raw
        self.u8 = np.frombuffer(raw, dtype=np.uint8)
        self.offs = np.asarray(offs).astype(np.int64)
        self.lens = np.asarray(lens).astype(np.int64)
        self.text = text
        self._mat = None

    def __len__(self) -> int:
        return int(self.lens.shape[0])

    def __getitem__(self, i):
        if isinstance(i, slice):
            return self.materialize()[i]
        o = int(self.offs[i])
        return self.text[o:o + int(self.lens[i])]

    def __iter__(self):
        return iter(self.materialize())

    def materialize(self) -> list:
        """Per-value string list (cached: the slow paths that need one
        value usually go on to need them all)."""
        m = self._mat
        if m is None:
            t = self.text
            ends = (self.offs + self.lens).tolist()
            m = self._mat = [t[s:e]
                             for s, e in zip(self.offs.tolist(), ends)]
        return m

    def wire_arena(self):
        """(arena bytes, u32 offsets, u32 lengths) for re-encoding this
        column into a fresh i1 frame (shard re-route / spool) without
        re-joining strings."""
        return (self.raw, self.offs.astype(np.uint32),
                self.lens.astype(np.uint32))


def _gather(ac: ArenaColumn, idx: np.ndarray):
    """Rows `idx` of an arena column -> one dense (sub, offs, lens)
    sub-arena in `idx` order (a single fancy index, no Python loop)."""
    lens = ac.lens[idx]
    total = int(lens.sum())
    offs = np.zeros(idx.shape[0], dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    if total:
        src = np.repeat(ac.offs[idx] - offs, lens) \
            + np.arange(total, dtype=np.int64)
        sub = ac.u8[src]
    else:
        sub = np.zeros(0, dtype=np.uint8)
    return sub, offs, lens


def _materialize(sub: np.ndarray, offs: np.ndarray,
                 lens: np.ndarray) -> list:
    t = sub.tobytes().decode("utf-8")
    ends = (offs + lens).tolist()
    return [t[s:e] for s, e in zip(offs.tolist(), ends)]


def encode_arena_column(name: str, sub: np.ndarray, offs: np.ndarray,
                        lens: np.ndarray) -> EncodedColumn:
    """`encode_values` over one dense ASCII sub-arena (offs = exclusive
    cumsum of lens), without materializing per-row strings on the
    proven paths.

    BYTE-EXACT contract: returns exactly what
    ``encode_values(name, _materialize(sub, offs, lens))`` would — the
    differential test in tests/test_block_build.py pins it.  Every gate
    below either proves the serial outcome vectorized or falls back to
    the serial code itself."""
    n = int(lens.shape[0])
    assert n > 0
    # NUL bytes defeat the padded-matrix trials (numpy S/U dtypes pad
    # with NUL, so "12\x00" would alias "12" and wrongly round-trip);
    # vanishingly rare in log data -> serial path wholesale
    if int(sub.shape[0]) and bool((sub == 0).any()):
        return encode_values(name, _materialize(sub, offs, lens))

    # const: uniform length + every padded row equals the first
    first_len = int(lens[0])
    if bool((lens == first_len).all()):
        if first_len == 0 or bool(
                (sub.reshape(n, first_len) == sub[:first_len]).all()):
            return EncodedColumn(
                name=name, vtype=VT_CONST,
                const_value=sub[:first_len].tobytes().decode("utf-8"))

    W = int(lens.max())
    # dict (<=8 distinct, <=256 total distinct bytes): any single value
    # over MAX_DICT_BYTES already overflows the distinct-bytes budget,
    # so W also bounds the padded matrix
    if W <= MAX_DICT_BYTES:
        col = _try_dict_arena(name, sub, offs, lens, n, W)
        if col is not None:
            return col

    first = sub[:first_len].tobytes().decode("utf-8")
    if _typed_gate(first):
        # pad into S<W> then cast to U<W>: exact for ASCII, and
        # identical to np.asarray(values, dtype="U") because no value
        # carries a NUL (guarded above) and W == max char length
        arr = _padded_u(sub, offs, lens, n, W)
        col = try_typed_encoding(
            name, arr, first, lambda: _materialize(sub, offs, lens))
        if col is not None:
            return col

    # raw string arena: the gathered sub-arena IS the payload
    return EncodedColumn(name=name, vtype=VT_STRING, arena=sub,
                         offsets=offs, lengths=lens)


def _typed_gate(first: str) -> bool:
    """True when ANY numeric/IPv4/ISO8601 trial could fire for a column
    whose first value is `first` — the padded-matrix cast is only paid
    when it can pay off.  Exact: each serial trial's own gate is either
    a first-value check replicated here, or (float64) numpy's astype,
    which parses element 0 first — so a False here means every serial
    trial returns None too."""
    from .values_encoder import _IPV4_RE
    if first[:1].isdigit() or first[:1] == "-":
        return True
    if _IPV4_RE.match(first):
        return True
    if len(first) >= 20 and first[4:5] == "-" and first.endswith("Z"):
        return True
    try:
        np.asarray([first], dtype="U").astype(np.float64)
        return True
    except ValueError:
        return False


def _padded_u(sub: np.ndarray, offs: np.ndarray, lens: np.ndarray,
              n: int, W: int) -> np.ndarray:
    """The rows as one U<W> array — element-for-element what
    ``np.asarray(values, dtype="U")`` gives the serial encoder: W is
    the max byte length (== max char length: the arena is ASCII here),
    NUL-free values make the S->U zero-padding unambiguous, and the
    S->U cast decodes ASCII strictly."""
    mat = np.zeros((n, W), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        row = np.repeat(np.arange(n, dtype=np.int64) * W, lens)
        inrow = np.arange(total, dtype=np.int64) - np.repeat(offs, lens)
        mat.reshape(-1)[row + inrow] = sub
    return mat.reshape(-1).view(f"S{W}").astype(f"U{W}")


def _void_rows(sub: np.ndarray, offs: np.ndarray, lens: np.ndarray,
               n: int, W: int) -> np.ndarray:
    """(n,) void view of the rows padded to W bytes, with a u16
    little-endian length suffix so "a" and "a\\x00...pad" can never
    collide (the length is part of the key)."""
    Wp = W + 2
    mat = np.zeros((n, Wp), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        row = np.repeat(np.arange(n, dtype=np.int64) * Wp, lens)
        inrow = np.arange(total, dtype=np.int64) - np.repeat(offs, lens)
        mat.reshape(-1)[row + inrow] = sub
    mat[:, W] = (lens & 0xFF).astype(np.uint8)
    mat[:, W + 1] = (lens >> 8).astype(np.uint8)
    return mat.reshape(-1).view(np.dtype((np.void, Wp)))


_DICT_PREGATE_ROWS = 512


def _try_dict_arena(name: str, sub: np.ndarray, offs: np.ndarray,
                    lens: np.ndarray, n: int, W: int):
    """Vectorized VT_DICT trial: distinct rows via np.unique over a
    padded void view, ids remapped to FIRST-SEEN order (the serial
    scan's assignment order).  None on any budget overflow — exactly
    when the serial scan rejects."""
    if n > _DICT_PREGATE_ROWS:
        # exact pre-gate on a prefix: distinctness and the distinct-
        # bytes total only grow with more rows, so a prefix that
        # already overflows either budget rejects the whole column —
        # high-cardinality string columns never pay the full matrix
        p = _DICT_PREGATE_ROWS
        pend = int(offs[p - 1] + lens[p - 1])
        pu, pidx = np.unique(
            _void_rows(sub[:pend], offs[:p], lens[:p], p, W),
            return_index=True)
        if pu.shape[0] > MAX_DICT_ENTRIES or \
                int(lens[pidx].sum()) > MAX_DICT_BYTES:
            return None
    uniq, first_idx, inv = np.unique(
        _void_rows(sub, offs, lens, n, W),
        return_index=True, return_inverse=True)
    k = int(uniq.shape[0])
    if k > MAX_DICT_ENTRIES:
        return None
    if int(lens[first_idx].sum()) > MAX_DICT_BYTES:
        return None
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(k, dtype=np.uint8)
    rank[order] = np.arange(k, dtype=np.uint8)
    dvals = []
    for i in first_idx[order].tolist():
        o = int(offs[i])
        dvals.append(sub[o:o + int(lens[i])].tobytes().decode("utf-8"))
    return EncodedColumn(name=name, vtype=VT_DICT, dict_values=dvals,
                         ids=rank[inv.reshape(-1)])


# ---------------- the shared build pool ----------------

# live (unclosed) pools, for the vlsan thread sweep: a vl-block-build
# worker owned by a still-reachable DataDB is infrastructure, not a
# leak (mirrors tpu/batch.py's live_prefetch_pools contract)
_live_pools: "weakref.WeakSet[BuildPool]" = weakref.WeakSet()


def live_build_pools() -> int:
    """Total worker threads live un-closed pools may own.  A pool whose
    DataDB closed contributes 0 — close() joins its workers."""
    total = 0
    for p in list(_live_pools):
        ex = p._ex
        if ex is not None:
            total += ex._max_workers
    return total


class BuildPool:
    """Lazily-spun ThreadPoolExecutor for block builds, owned by one
    DataDB: created on the first parallel build, joined by close().
    At VL_BLOCK_BUILD_THREADS<=1, executor() returns None and every
    caller runs serial — the 0/1 fallback the tests pin."""

    def __init__(self):
        self._mu = threading.Lock()
        self._ex = None
        self._closed = False
        _live_pools.add(self)

    def executor(self):
        n = build_threads()
        if n <= 1:
            return None
        with self._mu:
            if self._closed:
                return None
            if self._ex is None:
                from concurrent.futures import ThreadPoolExecutor
                self._ex = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="vl-block-build")
            return self._ex

    def close(self) -> None:
        with self._mu:
            ex, self._ex = self._ex, None
            self._closed = True
        if ex is not None:
            # join: an un-joined worker is a non-daemon thread the
            # vlsan leak sweep rightly flags once its owner is gone
            ex.shutdown(wait=True)


_WINDOW_PER_WORKER = 2


def run_tasks(tasks, pool) -> list:
    """Run zero-arg build tasks, returning results in SUBMISSION order
    (deterministic output regardless of worker scheduling).  `tasks`
    may be a lazy iterable: with a pool, a bounded window of 2x workers
    keeps the planner (chunk slicing, arena gathers) one step ahead of
    the encoders without materializing every chunk up front."""
    if pool is None:
        return [t() for t in tasks]
    window = max(2, pool._max_workers * _WINDOW_PER_WORKER)
    out: list = []
    pending: deque = deque()
    for t in tasks:
        pending.append(pool.submit(t))
        if len(pending) >= window:
            out.append(pending.popleft().result())
    while pending:
        out.append(pending.popleft().result())
    return out


# ---------------- batch -> block tasks ----------------

def _chunk_task(sid, ts: np.ndarray, chunk_cols: list, tags: str):
    """One (stream, chunk) build task.  chunk_cols: (name, payload)
    pairs in schema order; payload is either a value list (serial
    encode) or an (ArenaColumn, row-index array) pair gathered and
    encoded inside the task — on a pool, the gather itself runs on the
    worker."""
    def task() -> BlockData:
        nrows = int(ts.shape[0])
        columns: list = []
        const_columns: list = []
        for name, payload in chunk_cols:
            arena = None
            if type(payload) is tuple:
                ac, idx = payload
                arena = _gather(ac, idx)
                col = encode_arena_column(name, *arena)
            else:
                col = encode_values(name, payload)
            if col.vtype == VT_CONST:
                const_columns.append((name, col.const_value))
            else:
                if arena is not None and col.vtype not in (VT_CONST,
                                                           VT_DICT,
                                                           VT_STRING):
                    _typed_column_bloom(col, arena)
                else:
                    build_column_bloom(col, nrows)
                columns.append(col)
        return BlockData(stream_id=sid, timestamps=ts, columns=columns,
                         const_columns=const_columns,
                         stream_tags_str=tags)
    return task


def _typed_column_bloom(col: EncodedColumn, arena) -> None:
    """Token bloom for a typed (numeric/ipv4/iso) column straight from
    its pre-encode arena slice, skipping the serial path's per-row
    decode_values + tokenize_string loop.  Same stored bytes: the VT
    round trip is exact (encode verified it), so the decoded strings
    ARE the arena's values and the distinct-token-hash SET is equal —
    and bloom/sb/xor/maplet builds are all order-independent bit
    scatters or sorts over that set."""
    sub, offs, lens = arena
    from .. import native
    hashes = native.unique_token_hashes_native(sub, offs, lens)
    if hashes is None:
        from ..utils.hashing import hash_tokens
        from ..utils.tokenizer import tokenize_arena, unique_tokens_bytes
        ts_, te_, _ = tokenize_arena(sub, offs, lens)
        hashes = hash_tokens(unique_tokens_bytes(sub, ts_, te_))
    col.token_hashes = hashes
    col.bloom = bloom_build(hashes)


def build_columns_blocks(lc, pool=None) -> list:
    """LogColumns -> (stream, time)-sorted BlockData list: the body of
    LogColumns.build_blocks, lifted here so the independent chunk
    tasks can run on a DataDB's BuildPool.  Streams spanning MULTIPLE
    schema groups route through the row path so one call still yields
    non-overlapping time-sorted blocks per stream (the flush merger's
    within-part invariant).  Task submission order and the final
    stable sort are both deterministic, so the result is identical at
    any thread count."""
    gcount: dict = {}
    for g in lc.groups.values():
        for sid, _t, _s in g.streams:
            gcount[sid] = gcount.get(sid, 0) + 1
    slow: list = []          # (sid, ts, fields, tags) across groups

    def plan():
        for g in lc.groups.values():
            n = len(g.ts)
            if not n:
                continue
            ts = np.asarray(g.ts, dtype=np.int64)
            # per-stream rank in StreamID order == the row path's
            # (tenant, hi, lo) lexsort order (StreamID is order=True)
            by_sid = sorted(range(len(g.streams)),
                            key=lambda k: g.streams[k][0])
            rank = np.empty(len(g.streams), dtype=np.int64)
            for r, k in enumerate(by_sid):
                rank[k] = r
            rr = rank[np.asarray(g.sref, dtype=np.int64)]
            order = np.lexsort((ts, rr))
            rro = rr[order]
            bounds = [0] + (np.nonzero(np.diff(rro))[0] + 1).tolist() \
                + [n]
            for b in range(len(bounds) - 1):
                idxs = order[bounds[b]:bounds[b + 1]]
                sid, _tenant, tags = g.streams[g.sref[idxs[0]]]
                if gcount[sid] > 1:
                    for k in idxs.tolist():
                        fields = [(nm, c[k])
                                  for nm, c in zip(g.names, g.cols)]
                        slow.append((sid, g.ts[k], fields, tags))
                    continue
                run_ts = ts[idxs]
                # per-row size estimates: arena columns read byte
                # lengths directly (== char lengths, ASCII-gated);
                # list columns pay map(len) once per run
                rb = np.zeros(idxs.shape[0], dtype=np.int64)
                il = None
                mats: list = []
                for nm, c in zip(g.names, g.cols):
                    if type(c) is ArenaColumn:
                        rb += c.lens[idxs]
                        mats.append(None)
                    else:
                        if il is None:
                            il = idxs.tolist()
                        vals = [c[k] for k in il]
                        rb += np.fromiter(map(len, vals),
                                          dtype=np.int64,
                                          count=len(vals))
                        mats.append(vals)
                    rb += len(nm) + 16
                cum = np.cumsum(rb + 8)
                s = 0
                nrun = int(idxs.shape[0])
                while s < nrun:
                    e = chunk_end(cum, s)
                    chunk_cols = []
                    for nm, c, vals in zip(g.names, g.cols, mats):
                        if vals is None:
                            chunk_cols.append((nm, (c, idxs[s:e])))
                        else:
                            chunk_cols.append((nm, vals[s:e]))
                    yield _chunk_task(sid, run_ts[s:e], chunk_cols,
                                      tags)
                    s = e

    out = run_tasks(plan(), pool)
    if slow:
        slow.sort(key=lambda r: (r[0], r[1]))
        i = 0
        while i < len(slow):
            sid = slow[i][0]
            j = i
            while j < len(slow) and slow[j][0] == sid:
                j += 1
            run = slow[i:j]
            out.extend(build_blocks(
                sid, np.array([r[1] for r in run], dtype=np.int64),
                [r[2] for r in run], stream_tags_str=run[0][3]))
            i = j
    # global (stream_id, min_ts) order across schema groups: the flush
    # merger's k-way heap requires each part's block list sorted this
    # way (datadb.merge_block_streams input invariant)
    out.sort(key=lambda b: (b.stream_id, int(b.timestamps[0])))
    return out


def build_log_rows_blocks(lr, pool=None) -> list:
    """LogRows -> (stream_id, ts)-sorted BlockData list (the body of
    block.blocks_from_log_rows, chunk tasks pool-runnable)."""
    n = len(lr)
    if n == 0:
        return []
    # vectorized (stream_id, ts) sort: np.lexsort beats a per-row
    # Python key lambda ~20x on large batches (the ingest hot path)
    acct = np.fromiter((s.tenant.account_id for s in lr.stream_ids),
                       dtype=np.int64, count=n)
    proj = np.fromiter((s.tenant.project_id for s in lr.stream_ids),
                       dtype=np.int64, count=n)
    hi = np.fromiter((s.hi for s in lr.stream_ids), dtype=np.uint64,
                     count=n)
    lo = np.fromiter((s.lo for s in lr.stream_ids), dtype=np.uint64,
                     count=n)
    ts_arr = np.asarray(lr.timestamps, dtype=np.int64)
    order = np.lexsort((ts_arr, lo, hi, proj, acct)).tolist()

    def plan():
        i = 0
        while i < n:
            sid = lr.stream_ids[order[i]]
            j = i
            while j < n and lr.stream_ids[order[j]] == sid:
                j += 1
            idxs = order[i:j]
            ts = np.fromiter((lr.timestamps[k] for k in idxs),
                             dtype=np.int64, count=j - i)
            rows = [lr.rows[k] for k in idxs]
            tags = lr.stream_tags_str[idxs[0]]
            cum = row_cost_cum(rows)
            s = 0
            while s < len(rows):
                e = chunk_end(cum, s)
                yield (lambda sid=sid, cts=ts[s:e], crows=rows[s:e],
                       ctags=tags:
                       _build_one_block(sid, cts, crows, ctags))
                s = e
            i = j

    return run_tasks(plan(), pool)
