"""Storage root: per-day partitions, retention, ingestion entry point.

Reference: lib/logstorage/storage.go — owns the partition list keyed by UTC
day (dirs named YYYYMMDD — storage.go:326), splits incoming row batches by day
(storage.go:525-582), runs retention deletion hourly (storage.go:347-387) and
a max-disk-usage watcher (storage.go:389-443), and exposes DebugFlush /
MustForceMerge / UpdateStats.
"""

from __future__ import annotations

import datetime
import os
import shutil
import threading
import time

from ..obs import ingestledger
from .log_rows import LogRows, TenantID
from .partition import Partition

NSECS_PER_DAY = 86400 * 1_000_000_000
PARTITIONS_DIRNAME = "partitions"


def _columns_tenant_stats(lc, out: dict) -> None:
    """Accumulate tenant -> [rows, max_ts_ns] over one columnar batch
    (one bincount + maximum.at per group — never per row in Python)."""
    import numpy as np
    for g in lc.groups.values():
        if not g.ts:
            continue
        sref = np.asarray(g.sref, dtype=np.int64)
        ts = np.asarray(g.ts, dtype=np.int64)
        counts = np.bincount(sref, minlength=len(g.streams))
        maxs = np.full(len(g.streams), -1, dtype=np.int64)
        np.maximum.at(maxs, sref, ts)
        for (_sid, tenant, _tags), c, m in zip(
                g.streams, counts.tolist(), maxs.tolist()):
            if c:
                cell = out.setdefault(tenant, [0, 0])
                cell[0] += c
                cell[1] = max(cell[1], m)


def _columns_tenant_dropped(lc, min_ts: int,
                            max_ts: int) -> tuple[dict, dict]:
    """Per-tenant too_old / too_new row counts for a columnar batch —
    only computed when the range check actually dropped rows."""
    import numpy as np
    old: dict = {}
    new: dict = {}
    for g in lc.groups.values():
        if not g.ts:
            continue
        ts = np.asarray(g.ts, dtype=np.int64)
        sref = np.asarray(g.sref, dtype=np.int64)
        for mask, acc in ((ts < min_ts, old), (ts > max_ts, new)):
            if mask.any():
                counts = np.bincount(sref[mask],
                                     minlength=len(g.streams))
                for (_sid, tenant, _tags), c in zip(
                        g.streams, counts.tolist()):
                    if c:
                        acc[tenant] = acc.get(tenant, 0) + c
    return old, new


def day_from_ts(ts_ns: int) -> int:
    return ts_ns // NSECS_PER_DAY


def day_dir_name(day: int) -> str:
    d = datetime.datetime.fromtimestamp(day * 86400, datetime.timezone.utc)
    return d.strftime("%Y%m%d")


def day_from_dir_name(name: str) -> int:
    d = datetime.datetime.strptime(name, "%Y%m%d") \
        .replace(tzinfo=datetime.timezone.utc)
    return int(d.timestamp()) // 86400


class Storage:
    def __init__(self, path: str, retention_days: float = 7.0,
                 flush_interval: float = 5.0, future_retention_days: float = 2.0,
                 max_disk_usage_bytes: int = 0):
        self.path = path
        self.retention_days = retention_days
        self.future_retention_days = future_retention_days
        self.flush_interval = flush_interval
        self.max_disk_usage_bytes = max_disk_usage_bytes
        self._lock = threading.Lock()
        self.partitions: dict[int, Partition] = {}
        self.is_read_only = False
        self.rows_dropped_too_old = 0
        self.rows_dropped_too_new = 0
        os.makedirs(self._pdir(), exist_ok=True)
        for entry in sorted(os.listdir(self._pdir())):
            if entry.endswith(".tmp"):
                shutil.rmtree(os.path.join(self._pdir(), entry),
                              ignore_errors=True)
                continue
            try:
                day = day_from_dir_name(entry)
            except ValueError:
                continue
            self.partitions[day] = Partition(
                os.path.join(self._pdir(), entry), day,
                flush_interval=flush_interval)
        self._stop = threading.Event()
        self._retention_thread = threading.Thread(
            target=self._watch_retention, daemon=True)
        self._retention_thread.start()
        self._disk_thread = None
        if max_disk_usage_bytes > 0:
            self._disk_thread = threading.Thread(
                target=self._watch_disk_usage, daemon=True)
            self._disk_thread.start()

    def _pdir(self) -> str:
        return os.path.join(self.path, PARTITIONS_DIRNAME)

    # ---- ingestion ----
    def must_add_rows(self, lr: LogRows) -> None:
        """Split a batch by UTC day and add to the right partitions."""
        if self.is_read_only:
            raise RuntimeError("storage is read-only (disk usage limit)")
        n = len(lr)
        if n == 0:
            return
        now_ns = time.time_ns()
        min_ts = now_ns - int(self.retention_days * NSECS_PER_DAY)
        max_ts = now_ns + int(self.future_retention_days * NSECS_PER_DAY)
        # conservation-ledger attribution only for batch-tracked flows
        # (the ambient ctx gates it): direct writes — tests, journal
        # self-ingest — never rolled `accepted`, so they must not roll
        # `stored`/`dropped` either
        ctx = ingestledger.current_batch()
        stored: dict = {}        # tenant -> [rows, max_ts_ns]
        dropped_old: dict = {}
        dropped_new: dict = {}
        by_day: dict[int, list[int]] = {}
        for i, ts in enumerate(lr.timestamps):
            if ts < min_ts:
                self.rows_dropped_too_old += 1
                if ctx is not None:
                    t = lr.tenants[i]
                    dropped_old[t] = dropped_old.get(t, 0) + 1
                continue
            if ts > max_ts:
                self.rows_dropped_too_new += 1
                if ctx is not None:
                    t = lr.tenants[i]
                    dropped_new[t] = dropped_new.get(t, 0) + 1
                continue
            by_day.setdefault(day_from_ts(ts), []).append(i)
            if ctx is not None:
                cell = stored.setdefault(lr.tenants[i], [0, 0])
                cell[0] += 1
                cell[1] = max(cell[1], ts)
        for day, idxs in by_day.items():
            pt = self._get_partition(day)
            if len(by_day) == 1 and len(idxs) == n:
                pt.must_add_rows(lr)
            else:
                sub = LogRows()
                for i in idxs:
                    sub.timestamps.append(lr.timestamps[i])
                    sub.rows.append(lr.rows[i])
                    sub.stream_ids.append(lr.stream_ids[i])
                    sub.stream_tags_str.append(lr.stream_tags_str[i])
                    sub.tenants.append(lr.tenants[i])
                pt.must_add_rows(sub)
        if ctx is not None:
            self._ledger_rolls(stored, dropped_old, dropped_new)

    def must_add_columns(self, lc) -> None:
        """Columnar-batch twin of must_add_rows (LogColumns fast path)."""
        if self.is_read_only:
            raise RuntimeError("storage is read-only (disk usage limit)")
        if lc.nrows == 0:
            return
        now_ns = time.time_ns()
        min_ts = now_ns - int(self.retention_days * NSECS_PER_DAY)
        max_ts = now_ns + int(self.future_retention_days * NSECS_PER_DAY)
        ctx = ingestledger.current_batch()
        dropped_old: dict = {}
        dropped_new: dict = {}
        by_day, old, new = lc.split_by_day(min_ts, max_ts, NSECS_PER_DAY)
        self.rows_dropped_too_old += old
        self.rows_dropped_too_new += new
        if ctx is not None and (old or new):
            dropped_old, dropped_new = _columns_tenant_dropped(
                lc, min_ts, max_ts)
        stored: dict = {}
        for day, sub in by_day.items():
            self._get_partition(day).must_add_columns(sub)
            if ctx is not None:
                _columns_tenant_stats(sub, stored)
        if ctx is not None:
            self._ledger_rolls(stored, dropped_old, dropped_new)

    @staticmethod
    def _ledger_rolls(stored: dict, dropped_old: dict,
                      dropped_new: dict) -> None:
        """Terminal conservation rolls for one batch-tracked must_add:
        `stored` advances the tenant's freshness watermark with the max
        stored row time; range-check drops take the ledger's reasoned
        drop exit (the vlint drop-discipline contract)."""
        for t, (rows, max_ts_ns) in stored.items():
            ingestledger.note_stored(t, rows,
                                     max_ts_unix=max_ts_ns / 1e9)
        for t, rows in dropped_old.items():
            ingestledger.note_dropped(t, rows, "too_old")
        for t, rows in dropped_new.items():
            ingestledger.note_dropped(t, rows, "too_new")

    def _get_partition(self, day: int) -> Partition:
        with self._lock:
            pt = self.partitions.get(day)
            if pt is None:
                path = os.path.join(self._pdir(), day_dir_name(day))
                pt = Partition(path, day, flush_interval=self.flush_interval)
                self.partitions[day] = pt
            return pt

    # ---- query support ----
    def select_partitions(self, min_ts: int, max_ts: int) -> list[Partition]:
        lo = day_from_ts(min_ts)
        hi = day_from_ts(max_ts)
        with self._lock:
            return [p for d, p in sorted(self.partitions.items())
                    if lo <= d <= hi]

    # ---- maintenance ----
    def debug_flush(self) -> None:
        with self._lock:
            parts = list(self.partitions.values())
        for p in parts:
            p.debug_flush()

    def must_force_merge(self, partition_prefix: str = "") -> None:
        with self._lock:
            parts = [(d, p) for d, p in self.partitions.items()
                     if day_dir_name(d).startswith(partition_prefix)]
        for _, p in parts:
            p.force_merge()

    def _watch_retention(self) -> None:
        while not self._stop.wait(3600.0):
            try:
                self.drop_expired_partitions()
            # vlint: allow-broad-except(retention watcher must survive)
            except Exception:  # pragma: no cover
                pass

    def _watch_disk_usage(self) -> None:
        # reference watchMaxDiskSpaceUsage (storage.go:389-443): when the
        # data dir exceeds the limit, drop the oldest partitions to fit
        while not self._stop.wait(10.0):
            try:
                self.enforce_max_disk_usage()
            # vlint: allow-broad-except(disk watcher must survive)
            except Exception:  # pragma: no cover
                pass

    def _disk_usage_bytes(self) -> int:
        total = 0
        for root, _dirs, files in os.walk(self.path):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except OSError:
                    pass
        return total

    def enforce_max_disk_usage(self) -> list[int]:
        """Drop oldest partitions while over max_disk_usage_bytes."""
        if self.max_disk_usage_bytes <= 0:
            return []
        dropped: list[int] = []
        while self._disk_usage_bytes() > self.max_disk_usage_bytes:
            with self._lock:
                days = sorted(self.partitions)
                if len(days) <= 1:
                    break  # never drop the newest partition
                day = days[0]
                p = self.partitions.pop(day)
            p.close()
            shutil.rmtree(p.path, ignore_errors=True)
            dropped.append(day)
        return dropped

    def drop_expired_partitions(self, now_ns: int | None = None) -> list[int]:
        """Delete partitions fully older than the retention window."""
        if now_ns is None:
            now_ns = time.time_ns()
        min_day = day_from_ts(now_ns - int(self.retention_days
                                           * NSECS_PER_DAY))
        dropped = []
        with self._lock:
            for day in sorted(self.partitions):
                if day < min_day:
                    dropped.append(day)
            parts = [(d, self.partitions.pop(d)) for d in dropped]
        for day, p in parts:
            p.close()
            shutil.rmtree(p.path, ignore_errors=True)
        return dropped

    def update_stats(self) -> dict:
        with self._lock:
            parts = list(self.partitions.values())
        agg = {
            "partitions": len(parts), "streams": 0, "inmemory_rows": 0,
            "file_rows": 0, "small_rows": 0, "big_rows": 0,
            "inmemory_parts": 0, "small_parts": 0,
            "big_parts": 0, "compressed_size": 0, "uncompressed_size": 0,
            "pending_merges": 0, "merges_done": 0,
            "flush_age_seconds": 0.0,
            "rows_dropped_too_old": self.rows_dropped_too_old,
            "rows_dropped_too_new": self.rows_dropped_too_new,
            "is_read_only": self.is_read_only,
        }
        for p in parts:
            s = p.stats()
            for k in ("streams", "inmemory_rows", "file_rows",
                      "small_rows", "big_rows",
                      "inmemory_parts", "small_parts", "big_parts",
                      "compressed_size", "uncompressed_size",
                      "pending_merges", "merges_done"):
                agg[k] += s[k]
            # the staleness signal is the WORST partition's flush age
            agg["flush_age_seconds"] = max(agg["flush_age_seconds"],
                                           s["flush_age_seconds"])
        return agg

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            parts = list(self.partitions.values())
            self.partitions.clear()
        for p in parts:
            p.close()
