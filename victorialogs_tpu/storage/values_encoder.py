"""Per-block column type inference and encoding.

Mirrors the reference's values encoder semantics (lib/logstorage/
values_encoder.go:109-154): for each column in a block, try encodings in order
dict -> uint{8,16,32,64} -> int64 -> float64 -> IPv4 -> ISO8601 timestamp ->
raw string, accepting an encoding only when decoding reproduces every original
string byte-for-byte (round-trip property).  Numeric columns additionally
record min/max for header-level range pruning.

Unlike the reference (per-value byte parsing in Go), attempts are vectorized
with numpy over the whole column; the accepted representation *is* the
in-memory query-time representation (typed numpy arrays / byte arenas), which
is also exactly what the TPU staging path uploads.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

# value types (stable on-disk ids)
VT_STRING = 0
VT_CONST = 1
VT_DICT = 2
VT_UINT8 = 3
VT_UINT16 = 4
VT_UINT32 = 5
VT_UINT64 = 6
VT_INT64 = 7
VT_FLOAT64 = 8
VT_IPV4 = 9
VT_TIMESTAMP_ISO8601 = 10

VT_NAMES = {
    VT_STRING: "string",
    VT_CONST: "const",
    VT_DICT: "dict",
    VT_UINT8: "uint8",
    VT_UINT16: "uint16",
    VT_UINT32: "uint32",
    VT_UINT64: "uint64",
    VT_INT64: "int64",
    VT_FLOAT64: "float64",
    VT_IPV4: "ipv4",
    VT_TIMESTAMP_ISO8601: "iso8601",
}

MAX_DICT_ENTRIES = 8  # reference: consts.go:61-70
MAX_DICT_BYTES = 256

_UINT_DTYPES = [(VT_UINT8, np.uint8), (VT_UINT16, np.uint16),
                (VT_UINT32, np.uint32), (VT_UINT64, np.uint64)]

_IPV4_RE = re.compile(r"^(\d{1,3})\.(\d{1,3})\.(\d{1,3})\.(\d{1,3})$")
_ISO8601_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2}):(\d{2})(?:\.(\d{1,9}))?Z$")


@dataclass
class EncodedColumn:
    """A type-encoded column for one block."""

    name: str
    vtype: int
    # payloads by type:
    const_value: str | None = None                 # VT_CONST
    dict_values: list[str] | None = None           # VT_DICT
    ids: np.ndarray | None = None                  # VT_DICT: uint8[R]
    nums: np.ndarray | None = None                 # numeric types
    arena: np.ndarray | None = None                # VT_STRING: uint8[N]
    offsets: np.ndarray | None = None              # VT_STRING: int64[R]
    lengths: np.ndarray | None = None              # VT_STRING: int64[R]
    min_val: float = 0.0                           # numeric min (as float)
    max_val: float = 0.0
    iso_frac_w: int = 0                            # VT_TIMESTAMP fractional digits
    bloom: np.ndarray | None = None                # uint64 words (set later)
    # distinct token hashes behind `bloom`, kept through the flush so
    # the seal-time filter-index build (storage/filterindex) doesn't
    # re-tokenize fresh blocks; absent on columns read back from disk
    token_hashes: np.ndarray | None = None
    _strings_cache: list[str] | None = field(default=None, repr=False)

    @property
    def type_name(self) -> str:
        return VT_NAMES[self.vtype]

    def num_rows(self, block_rows: int) -> int:
        return block_rows

    def to_strings(self, nrows: int) -> list[str]:
        """Decode back to the original string values (round-trip exact)."""
        if self._strings_cache is not None:
            return self._strings_cache
        out = decode_values(self, nrows)
        self._strings_cache = out
        return out


def _round_trip_uint(values: np.ndarray):
    try:
        u = values.astype(np.uint64)
    except (ValueError, OverflowError):
        return None
    back = u.astype(values.dtype)
    if back.shape != values.shape or not np.array_equal(back, values):
        return None
    return u


def _format_floats(f: np.ndarray) -> np.ndarray:
    # canonical float formatting = Python repr via numpy astype(U)
    return f.astype("U32")


def encode_values(name: str, values: list[str]) -> EncodedColumn:
    """Infer the tightest type for a column of strings and encode it."""
    nrows = len(values)
    assert nrows > 0
    first = values[0]

    # const
    all_same = True
    for v in values:
        if v != first:
            all_same = False
            break
    if all_same:
        return EncodedColumn(name=name, vtype=VT_CONST, const_value=first,
                             _strings_cache=values)

    # dict (<=8 distinct entries, <=256 total bytes)
    uniq: dict[str, int] = {}
    for v in values:
        if v not in uniq:
            if len(uniq) >= MAX_DICT_ENTRIES:
                uniq = None  # type: ignore
                break
            uniq[v] = len(uniq)
    if uniq is not None:
        dvals = list(uniq.keys())
        if sum(len(s.encode("utf-8")) for s in dvals) <= MAX_DICT_BYTES:
            ids = np.fromiter((uniq[v] for v in values), dtype=np.uint8,
                              count=nrows)
            return EncodedColumn(name=name, vtype=VT_DICT, dict_values=dvals,
                                 ids=ids, _strings_cache=values)

    arr = np.asarray(values, dtype="U")
    col = try_typed_encoding(name, arr, first, lambda: values)
    if col is not None:
        col._strings_cache = values
        return col

    # raw string arena
    bvals = [v.encode("utf-8") for v in values]
    lengths = np.fromiter((len(b) for b in bvals), dtype=np.int64, count=nrows)
    offsets = np.zeros(nrows, dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    arena = np.frombuffer(b"".join(bvals), dtype=np.uint8)
    return EncodedColumn(name=name, vtype=VT_STRING, arena=arena,
                         offsets=offsets, lengths=lengths,
                         _strings_cache=values)


def try_typed_encoding(name: str, arr: np.ndarray, first: str,
                       get_values) -> EncodedColumn | None:
    """The uint{8..64} -> int64 -> float64 -> IPv4 -> ISO8601 trial
    cascade over a prepared U-dtype array, or None when no typed
    encoding round-trips.  Shared by the row path above and the
    arena-fed columnar path (storage/block_build.encode_arena_column)
    so the two can never drift — both accept an encoding on exactly the
    same evidence.  `get_values` materializes the Python string list
    lazily: only the per-value IPv4/ISO8601 parsers walk it, so the
    arena path pays for it only when those trials actually fire."""
    # uint8..uint64
    if first[:1].isdigit():
        u = _round_trip_uint(arr)
        if u is not None:
            mx = int(u.max())
            for vt, dt in _UINT_DTYPES:
                if mx <= int(np.iinfo(dt).max):
                    return EncodedColumn(
                        name=name, vtype=vt, nums=u.astype(dt),
                        min_val=float(u.min()), max_val=float(mx))

    # int64
    if first[:1] == "-" or first[:1].isdigit():
        try:
            i = arr.astype(np.int64)
        except (ValueError, OverflowError):
            i = None
        if i is not None and np.array_equal(i.astype(arr.dtype), arr):
            return EncodedColumn(name=name, vtype=VT_INT64, nums=i,
                                 min_val=float(i.min()),
                                 max_val=float(i.max()))

    # float64 (round-trip through canonical formatting)
    try:
        f = arr.astype(np.float64)
    except ValueError:
        f = None
    if f is not None and np.isfinite(f).all():
        if np.array_equal(_format_floats(f).astype(arr.dtype), arr):
            return EncodedColumn(name=name, vtype=VT_FLOAT64, nums=f,
                                 min_val=float(f.min()),
                                 max_val=float(f.max()))

    # IPv4
    if _IPV4_RE.match(first):
        ip = _try_ipv4(get_values())
        if ip is not None:
            return EncodedColumn(name=name, vtype=VT_IPV4, nums=ip,
                                 min_val=float(ip.min()),
                                 max_val=float(ip.max()))

    # ISO8601 timestamp (uniform fractional width)
    if len(first) >= 20 and first[4:5] == "-" and first.endswith("Z"):
        parsed = _try_iso8601(get_values())
        if parsed is not None:
            ts, frac_w = parsed
            return EncodedColumn(name=name, vtype=VT_TIMESTAMP_ISO8601,
                                 nums=ts, min_val=float(ts.min()),
                                 max_val=float(ts.max()),
                                 iso_frac_w=frac_w)
    return None


def _try_ipv4(values: list[str]) -> np.ndarray | None:
    out = np.empty(len(values), dtype=np.uint32)
    for i, v in enumerate(values):
        m = _IPV4_RE.match(v)
        if m is None:
            return None
        a, b, c, d = m.groups()
        # reject non-canonical octets like "01"
        if (len(a) > 1 and a[0] == "0") or (len(b) > 1 and b[0] == "0") or \
           (len(c) > 1 and c[0] == "0") or (len(d) > 1 and d[0] == "0"):
            return None
        ai, bi, ci, di = int(a), int(b), int(c), int(d)
        if ai > 255 or bi > 255 or ci > 255 or di > 255:
            return None
        out[i] = (ai << 24) | (bi << 16) | (ci << 8) | di
    return out


_EPOCH_DAYS_CACHE: dict[tuple[int, int, int], int] = {}


def _days_from_civil(y: int, m: int, d: int) -> int:
    key = (y, m, d)
    v = _EPOCH_DAYS_CACHE.get(key)
    if v is None:
        # Howard Hinnant's civil-days algorithm
        y2 = y - (m <= 2)
        era = (y2 if y2 >= 0 else y2 - 399) // 400
        yoe = y2 - era * 400
        doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
        doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
        v = era * 146097 + doe - 719468
        _EPOCH_DAYS_CACHE[key] = v
    return v


def _try_iso8601(values: list[str]) -> tuple[np.ndarray, int] | None:
    """Parse strictly-formatted UTC timestamps into int64 nanos.

    Requires every value to share the same fractional-digit width so that
    formatting round-trips (reference requires one exact layout per block).
    """
    m0 = _ISO8601_RE.match(values[0])
    if m0 is None:
        return None
    frac0 = m0.group(7)
    frac_w = len(frac0) if frac0 is not None else 0
    out = np.empty(len(values), dtype=np.int64)
    for i, v in enumerate(values):
        m = _ISO8601_RE.match(v)
        if m is None:
            return None
        y, mo, d, h, mi, s, frac = m.groups()
        if (len(frac) if frac is not None else 0) != frac_w:
            return None
        mo_i, d_i, h_i, mi_i, s_i = int(mo), int(d), int(h), int(mi), int(s)
        if not (1 <= mo_i <= 12 and 1 <= d_i <= _days_in_month(int(y), mo_i)
                and h_i < 24 and mi_i < 60 and s_i < 60):
            return None
        days = _days_from_civil(int(y), mo_i, d_i)
        ns = ((days * 86400 + h_i * 3600 + mi_i * 60 + s_i) * 1_000_000_000)
        if frac_w:
            ns += int(frac) * 10 ** (9 - frac_w)
        out[i] = ns
    return out, frac_w


_MONTH_DAYS = (31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31)


def _days_in_month(y: int, m: int) -> int:
    if m == 2 and (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)):
        return 29
    return _MONTH_DAYS[m - 1]


def format_iso8601(ns: int, frac_w: int) -> str:
    days, rem = divmod(ns, 86400 * 1_000_000_000)
    # civil from days (inverse of _days_from_civil)
    z = days + 719468
    era = (z if z >= 0 else z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + (3 if mp < 10 else -9)
    y += m <= 2
    secs, frac_ns = divmod(rem, 1_000_000_000)
    h, rem_s = divmod(secs, 3600)
    mi, s = divmod(rem_s, 60)
    base = f"{y:04d}-{m:02d}-{d:02d}T{h:02d}:{mi:02d}:{s:02d}"
    if frac_w:
        frac = frac_ns // 10 ** (9 - frac_w)
        base += f".{frac:0{frac_w}d}"
    return base + "Z"


def decode_values(col: EncodedColumn, nrows: int) -> list[str]:
    """Decode a column back to its original strings."""
    vt = col.vtype
    if vt == VT_CONST:
        return [col.const_value] * nrows  # type: ignore[list-item]
    if vt == VT_DICT:
        dv = col.dict_values
        return [dv[i] for i in col.ids.tolist()]  # type: ignore[index]
    if vt in (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64, VT_INT64):
        return col.nums.astype("U20").tolist()  # type: ignore[union-attr]
    if vt == VT_FLOAT64:
        return _format_floats(col.nums).tolist()  # type: ignore[arg-type]
    if vt == VT_IPV4:
        n = col.nums
        return [f"{(v >> 24) & 255}.{(v >> 16) & 255}.{(v >> 8) & 255}.{v & 255}"
                for v in n.tolist()]
    if vt == VT_TIMESTAMP_ISO8601:
        return [format_iso8601(v, col.iso_frac_w) for v in col.nums.tolist()]
    # VT_STRING
    buf = col.arena.tobytes()
    offs = col.offsets.tolist()
    lens = col.lengths.tolist()
    return [buf[o:o + l].decode("utf-8", "replace")
            for o, l in zip(offs, lens)]
