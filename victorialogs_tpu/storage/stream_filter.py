"""Stream filters: `{label="value", other=~"re.*"}` matching over stream tags.

Reference: lib/logstorage/stream_filter.go (StreamFilter = OR-list of AND-lists
of tag filters with ops = != =~ !~), evaluated against the per-partition
stream index (indexdb.go:182-307).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


@dataclass(frozen=True)
class TagFilter:
    label: str
    op: str        # '=', '!=', '=~', '!~'
    value: str

    def matches(self, tags: dict[str, str]) -> bool:
        have = tags.get(self.label, "")
        if self.op == "=":
            return have == self.value
        if self.op == "!=":
            return have != self.value
        rx = _compiled(self.value)
        if self.op == "=~":
            return rx.fullmatch(have) is not None
        if self.op == "!~":
            return rx.fullmatch(have) is None
        raise ValueError(f"unknown tag filter op {self.op!r}")

    def to_string(self) -> str:
        return f'{self.label}{self.op}"{self.value}"'


_RX_CACHE: dict[str, re.Pattern] = {}


def _compiled(pattern: str) -> re.Pattern:
    rx = _RX_CACHE.get(pattern)
    if rx is None:
        rx = re.compile(pattern)
        if len(_RX_CACHE) > 1024:
            _RX_CACHE.clear()
        _RX_CACHE[pattern] = rx
    return rx


@dataclass(frozen=True)
class StreamFilter:
    """OR of AND-groups: [[f1, f2], [f3]] means (f1 AND f2) OR f3."""

    or_groups: tuple[tuple[TagFilter, ...], ...]

    def matches(self, tags: dict[str, str]) -> bool:
        for grp in self.or_groups:
            if all(tf.matches(tags) for tf in grp):
                return True
        return False

    def to_string(self) -> str:
        return "{" + " or ".join(
            ",".join(tf.to_string() for tf in grp) for grp in self.or_groups
        ) + "}"


def parse_stream_tags(tags_str: str) -> dict[str, str]:
    """Parse the canonical `{k="v",k2="v2"}` rendering back into a dict."""
    out: dict[str, str] = {}
    s = tags_str.strip()
    if not (s.startswith("{") and s.endswith("}")):
        return out
    s = s[1:-1]
    i = 0
    n = len(s)
    while i < n:
        eq = s.find("=", i)
        if eq < 0:
            break
        key = s[i:eq]
        i = eq + 1
        if i < n and s[i] == '"':
            i += 1
            buf = []
            while i < n:
                c = s[i]
                if c == "\\" and i + 1 < n:
                    buf.append(s[i + 1])
                    i += 2
                    continue
                if c == '"':
                    i += 1
                    break
                buf.append(c)
                i += 1
            out[key] = "".join(buf)
        if i < n and s[i] == ",":
            i += 1
    return out
