"""Ingestion row model: tenants, stream IDs, row batches.

Reference semantics: a log stream is identified by (tenantID, 128-bit hash of
the canonical sorted stream-label string) — lib/logstorage/stream_id.go:11-22,
tenant = (AccountID, ProjectID) — tenant_id.go.  `LogRows` is the arena-backed
ingestion batch that computes stream IDs from the configured stream fields and
applies ignore/extra-field rules — log_rows.go:21-57.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..utils.hashing import stream_id_hash


@dataclass(frozen=True, order=True)
class TenantID:
    account_id: int = 0
    project_id: int = 0

    def as_string(self) -> str:
        return f"{self.account_id}:{self.project_id}"

    @staticmethod
    def parse(s: str) -> "TenantID":
        if not s:
            return TenantID()
        parts = s.split(":")
        if len(parts) == 1:
            return TenantID(int(parts[0]), 0)
        return TenantID(int(parts[0]), int(parts[1]))


@dataclass(frozen=True, order=True)
class StreamID:
    tenant: TenantID
    hi: int
    lo: int

    def as_string(self) -> str:
        # matches the reference's _stream_id hex rendering:
        # 32 hex chars of the 128-bit hash (stream_id.go marshaling)
        return f"{self.tenant.account_id:08x}{self.tenant.project_id:08x}" \
               f"{self.hi:016x}{self.lo:016x}"

    @staticmethod
    def parse(s: str) -> "StreamID | None":
        if len(s) != 48:
            return None
        try:
            return StreamID(
                TenantID(int(s[0:8], 16), int(s[8:16], 16)),
                int(s[16:32], 16), int(s[32:48], 16))
        except ValueError:
            return None


def canonical_stream_tags(tags: list[tuple[str, str]]) -> str:
    """Canonical `{k1="v1",k2="v2"}` rendering, sorted by label name."""
    items = sorted(tags)
    inner = ",".join(f'{k}={_quote(v)}' for k, v in items)
    return "{" + inner + "}"


def _quote(v: str) -> str:
    return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'


@dataclass
class Row:
    """One log row: timestamp in ns + field name/value pairs."""

    timestamp: int
    fields: list[tuple[str, str]]

    def get(self, name: str) -> str:
        for k, v in self.fields:
            if k == name:
                return v
        return ""


@dataclass
class LogRows:
    """A batch of rows destined for one Storage, with per-row stream IDs.

    stream_fields: field names that define the stream (like `_stream_fields`).
    ignore_fields: field names (or `prefix.*` patterns) dropped at ingestion.
    extra_fields: fields force-added to every row.
    """

    stream_fields: list[str] = dc_field(default_factory=list)
    ignore_fields: list[str] = dc_field(default_factory=list)
    extra_fields: list[tuple[str, str]] = dc_field(default_factory=list)
    default_msg_value: str = ""

    timestamps: list[int] = dc_field(default_factory=list)
    rows: list[list[tuple[str, str]]] = dc_field(default_factory=list)
    stream_ids: list[StreamID] = dc_field(default_factory=list)
    stream_tags_str: list[str] = dc_field(default_factory=list)
    tenants: list[TenantID] = dc_field(default_factory=list)
    _stream_cache: dict = dc_field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.timestamps)

    def add(self, tenant: TenantID, timestamp: int,
            fields: list[tuple[str, str]]) -> None:
        if self.ignore_fields or self.extra_fields:
            fields = self._apply_field_rules(fields)
        # rename duplicate handling: keep the first occurrence of each name
        seen: set[str] = set()
        clean: list[tuple[str, str]] = []
        has_msg = False
        for k, v in fields:
            if k == "_time":
                continue
            if k == "_msg":
                has_msg = True
            if k in seen:
                continue
            seen.add(k)
            clean.append((k, v))
        if not has_msg and self.default_msg_value:
            clean.append(("_msg", self.default_msg_value))

        stream_tags = [(k, v) for k, v in clean if k in self.stream_fields] \
            if self.stream_fields else []
        key = (tenant, tuple(stream_tags))
        cached = self._stream_cache.get(key)
        if cached is None:
            tags_str = canonical_stream_tags(stream_tags)
            hi, lo = stream_id_hash(tags_str.encode("utf-8"))
            cached = (StreamID(tenant, hi, lo), tags_str)
            self._stream_cache[key] = cached
        sid, tags_str = cached

        self.timestamps.append(timestamp)
        self.rows.append(clean)
        self.stream_ids.append(sid)
        self.stream_tags_str.append(tags_str)
        self.tenants.append(tenant)

    def _apply_field_rules(
            self, fields: list[tuple[str, str]]) -> list[tuple[str, str]]:
        out = []
        for k, v in fields:
            drop = False
            for pat in self.ignore_fields:
                if pat.endswith("*"):
                    if k.startswith(pat[:-1]):
                        drop = True
                        break
                elif k == pat:
                    drop = True
                    break
            if not drop:
                out.append((k, v))
        for k, v in self.extra_fields:
            out.append((k, v))
        return out

    def reset(self) -> None:
        self.timestamps.clear()
        self.rows.clear()
        self.stream_ids.clear()
        self.stream_tags_str.clear()
        self.tenants.clear()


class LogColumns:
    """Columnar ingestion batch: rows grouped by field SCHEMA (the exact
    final name tuple), values accumulated per column, streams interned in
    a per-group table (rows carry a small int ref) — no per-row tuple
    lists anywhere.  This is the bulk fast path for high-rate protocol
    ingestion (jsonline): the reference gets the same effect from its
    arena-backed LogRows + per-CPU rowsBuffer shards (log_rows.go:21-57,
    datadb.go:667-747); in Python the win comes from replacing ~10
    per-row allocations with a handful of list appends and doing the
    (stream, time) sort per GROUP with numpy.

    Semantics contract (tested against the row path bit-for-bit): a row
    added as (names, values) here must produce exactly the rows that
    LogRows.add(fields=zip(names, values)) would — callers are expected
    to have already applied time-field extraction, msg renaming and
    field rules (server/vlinsert._SchemaPlan does this per schema, once).
    """

    def __init__(self):
        self.groups: dict[tuple, _ColGroup] = {}
        self.nrows = 0
        # batch-level registration set: sid -> tags_str
        self.stream_tags: dict = {}

    def group(self, names: tuple, stream_pos: tuple) -> "_ColGroup":
        g = self.groups.get(names)
        if g is None:
            g = self.groups[names] = _ColGroup(names, stream_pos)
        return g

    def add(self, g: "_ColGroup", tenant: TenantID, ts: int, values: list,
            sid: StreamID, tags: str) -> None:
        si = g.stream_idx.get(sid)
        if si is None:
            si = g.stream_idx[sid] = len(g.streams)
            g.streams.append((sid, tenant, tags))
            if sid not in self.stream_tags:
                self.stream_tags[sid] = tags
        g.ts.append(ts)
        g.sref.append(si)
        for col, v in zip(g.cols, values):
            col.append(v)
        self.nrows += 1

    def intern_stream(self, g: "_ColGroup", tenant: TenantID,
                      sid: StreamID, tags: str) -> int:
        """One stream -> its ref in g's table (registering it batch-wide
        on first sight).  Callers that cache the returned ref under a
        cheap key (vlinsert's per-group raw-value cache) skip the
        StreamID dataclass hash per ROW — it is paid once per unique
        stream here."""
        si = g.stream_idx.get(sid)
        if si is None:
            si = g.stream_idx[sid] = len(g.streams)
            g.streams.append((sid, tenant, tags))
            if sid not in self.stream_tags:
                self.stream_tags[sid] = tags
        return si

    def add_bulk_refs(self, g: "_ColGroup", ts_list: list,
                      col_lists: list, srefs: list) -> None:
        """Append many rows of ONE schema whose stream refs are already
        interned (via intern_stream) — the hot bulk path: per-column
        extends only, zero per-row dict lookups."""
        g.ts.extend(ts_list)
        g.sref.extend(srefs)
        for col, vals in zip(g.cols, col_lists):
            col.extend(vals)
        self.nrows += len(ts_list)

    def add_bulk(self, g: "_ColGroup", tenant: TenantID, ts_list: list,
                 col_lists: list, sid_list: list, tags_list: list) -> None:
        """Append many rows of ONE schema at once: per-column extends
        instead of per-row appends (the native-scanner ingest path)."""
        sidx = g.stream_idx
        streams = g.streams
        stags = self.stream_tags
        srefs = []
        ap = srefs.append
        for sid, tags in zip(sid_list, tags_list):
            si = sidx.get(sid)
            if si is None:
                si = sidx[sid] = len(streams)
                streams.append((sid, tenant, tags))
                if sid not in stags:
                    stags[sid] = tags
            ap(si)
        g.ts.extend(ts_list)
        g.sref.extend(srefs)
        for col, vals in zip(g.cols, col_lists):
            col.extend(vals)
        self.nrows += len(ts_list)

    def unique_streams(self) -> list:
        return list(self.stream_tags.items())

    def split_by_day(self, min_ts: int, max_ts: int, ns_per_day: int):
        """(day -> LogColumns, dropped_old, dropped_new).  Vectorized;
        the common single-day batch is returned without copying."""
        import numpy as np
        days = set()
        old = new = 0
        masks = {}
        for key, g in self.groups.items():
            ts = np.asarray(g.ts, dtype=np.int64)
            ok = (ts >= min_ts) & (ts <= max_ts)
            old += int((ts < min_ts).sum())
            new += int((ts > max_ts).sum())
            d = ts // ns_per_day
            masks[key] = (ts, ok, d)
            days.update(np.unique(d[ok]).tolist())
        if not days:
            return {}, old, new
        if len(days) == 1 and old == 0 and new == 0:
            return {next(iter(days)): self}, 0, 0
        out = {}
        for day in days:
            sub = LogColumns()
            for key, g in self.groups.items():
                ts, ok, d = masks[key]
                idxs = np.nonzero(ok & (d == day))[0]
                if not idxs.size:
                    continue
                sg = sub.group(g.names, g.stream_pos)
                for i in idxs.tolist():
                    sid, tenant, tags = g.streams[g.sref[i]]
                    sub.add(sg, tenant, g.ts[i],
                            [c[i] for c in g.cols], sid, tags)
            out[day] = sub
        return out, old, new

    def build_blocks(self, pool=None) -> list:
        """Encode the batch into columnar blocks, sorted by (stream,
        time).  The planning + encoding body lives in
        storage/block_build (ONE copy of the size-bounded chunking rule
        for the row and columnar paths): each independent (stream,
        chunk) task optionally runs on `pool` (a DataDB's build pool),
        assembled in submission order — the result is identical at any
        thread count."""
        from .block_build import build_columns_blocks
        return build_columns_blocks(self, pool)


class _ColGroup:
    """One schema group inside a LogColumns batch."""

    __slots__ = ("names", "stream_pos", "cols", "ts", "sref",
                 "streams", "stream_idx", "key_idx")

    def __init__(self, names: tuple, stream_pos: tuple):
        self.names = names
        self.stream_pos = stream_pos
        self.cols = [[] for _ in names]
        self.ts: list = []
        self.sref: list = []
        self.streams: list = []        # (sid, tenant, tags_str)
        self.stream_idx: dict = {}
        # optional producer-side cache: raw stream-value key -> sref,
        # so bulk producers skip the StreamID hash per row (vlinsert)
        self.key_idx: dict = {}
