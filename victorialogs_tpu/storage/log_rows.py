"""Ingestion row model: tenants, stream IDs, row batches.

Reference semantics: a log stream is identified by (tenantID, 128-bit hash of
the canonical sorted stream-label string) — lib/logstorage/stream_id.go:11-22,
tenant = (AccountID, ProjectID) — tenant_id.go.  `LogRows` is the arena-backed
ingestion batch that computes stream IDs from the configured stream fields and
applies ignore/extra-field rules — log_rows.go:21-57.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..utils.hashing import stream_id_hash


@dataclass(frozen=True, order=True)
class TenantID:
    account_id: int = 0
    project_id: int = 0

    def as_string(self) -> str:
        return f"{self.account_id}:{self.project_id}"

    @staticmethod
    def parse(s: str) -> "TenantID":
        if not s:
            return TenantID()
        parts = s.split(":")
        if len(parts) == 1:
            return TenantID(int(parts[0]), 0)
        return TenantID(int(parts[0]), int(parts[1]))


@dataclass(frozen=True, order=True)
class StreamID:
    tenant: TenantID
    hi: int
    lo: int

    def as_string(self) -> str:
        # matches the reference's _stream_id hex rendering:
        # 32 hex chars of the 128-bit hash (stream_id.go marshaling)
        return f"{self.tenant.account_id:08x}{self.tenant.project_id:08x}" \
               f"{self.hi:016x}{self.lo:016x}"

    @staticmethod
    def parse(s: str) -> "StreamID | None":
        if len(s) != 48:
            return None
        try:
            return StreamID(
                TenantID(int(s[0:8], 16), int(s[8:16], 16)),
                int(s[16:32], 16), int(s[32:48], 16))
        except ValueError:
            return None


def canonical_stream_tags(tags: list[tuple[str, str]]) -> str:
    """Canonical `{k1="v1",k2="v2"}` rendering, sorted by label name."""
    items = sorted(tags)
    inner = ",".join(f'{k}={_quote(v)}' for k, v in items)
    return "{" + inner + "}"


def _quote(v: str) -> str:
    return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'


@dataclass
class Row:
    """One log row: timestamp in ns + field name/value pairs."""

    timestamp: int
    fields: list[tuple[str, str]]

    def get(self, name: str) -> str:
        for k, v in self.fields:
            if k == name:
                return v
        return ""


@dataclass
class LogRows:
    """A batch of rows destined for one Storage, with per-row stream IDs.

    stream_fields: field names that define the stream (like `_stream_fields`).
    ignore_fields: field names (or `prefix.*` patterns) dropped at ingestion.
    extra_fields: fields force-added to every row.
    """

    stream_fields: list[str] = dc_field(default_factory=list)
    ignore_fields: list[str] = dc_field(default_factory=list)
    extra_fields: list[tuple[str, str]] = dc_field(default_factory=list)
    default_msg_value: str = ""

    timestamps: list[int] = dc_field(default_factory=list)
    rows: list[list[tuple[str, str]]] = dc_field(default_factory=list)
    stream_ids: list[StreamID] = dc_field(default_factory=list)
    stream_tags_str: list[str] = dc_field(default_factory=list)
    tenants: list[TenantID] = dc_field(default_factory=list)
    _stream_cache: dict = dc_field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.timestamps)

    def add(self, tenant: TenantID, timestamp: int,
            fields: list[tuple[str, str]]) -> None:
        if self.ignore_fields or self.extra_fields:
            fields = self._apply_field_rules(fields)
        # rename duplicate handling: keep the first occurrence of each name
        seen: set[str] = set()
        clean: list[tuple[str, str]] = []
        has_msg = False
        for k, v in fields:
            if k == "_time":
                continue
            if k == "_msg":
                has_msg = True
            if k in seen:
                continue
            seen.add(k)
            clean.append((k, v))
        if not has_msg and self.default_msg_value:
            clean.append(("_msg", self.default_msg_value))

        stream_tags = [(k, v) for k, v in clean if k in self.stream_fields] \
            if self.stream_fields else []
        key = (tenant, tuple(stream_tags))
        cached = self._stream_cache.get(key)
        if cached is None:
            tags_str = canonical_stream_tags(stream_tags)
            hi, lo = stream_id_hash(tags_str.encode("utf-8"))
            cached = (StreamID(tenant, hi, lo), tags_str)
            self._stream_cache[key] = cached
        sid, tags_str = cached

        self.timestamps.append(timestamp)
        self.rows.append(clean)
        self.stream_ids.append(sid)
        self.stream_tags_str.append(tags_str)
        self.tenants.append(tenant)

    def _apply_field_rules(
            self, fields: list[tuple[str, str]]) -> list[tuple[str, str]]:
        out = []
        for k, v in fields:
            drop = False
            for pat in self.ignore_fields:
                if pat.endswith("*"):
                    if k.startswith(pat[:-1]):
                        drop = True
                        break
                elif k == pat:
                    drop = True
                    break
            if not drop:
                out.append((k, v))
        for k, v in self.extra_fields:
            out.append((k, v))
        return out

    def reset(self) -> None:
        self.timestamps.clear()
        self.rows.clear()
        self.stream_ids.clear()
        self.stream_tags_str.clear()
        self.tenants.clear()
