"""Per-day partition: one indexdb + one datadb.

Reference: lib/logstorage/partition.go:19-35 — a partition pairs the stream
index with the LSM datadb for one UTC day; new streams are registered in the
indexdb *before* their rows reach the datadb (partition.go:120-163).
"""

from __future__ import annotations

import os

from .datadb import DataDB
from .indexdb import IndexDB
from .log_rows import LogRows

INDEXDB_DIRNAME = "indexdb"
DATADB_DIRNAME = "datadb"


class Partition:
    def __init__(self, path: str, day: int, flush_interval: float = 5.0):
        """day: days since unix epoch (partition dir named YYYYMMDD)."""
        self.path = path
        self.day = day
        os.makedirs(path, exist_ok=True)
        self.idb = IndexDB(os.path.join(path, INDEXDB_DIRNAME))
        self.ddb = DataDB(os.path.join(path, DATADB_DIRNAME),
                          flush_interval=flush_interval)

    def must_add_rows(self, lr: LogRows) -> None:
        # register unseen streams first so a crash between index write and
        # datadb write leaves only a harmless extra index entry
        seen = set()
        unseen: list[tuple] = []
        for sid, tags in zip(lr.stream_ids, lr.stream_tags_str):
            if sid in seen:
                continue
            seen.add(sid)
            if not self.idb.has_stream_id(sid):
                unseen.append((sid, tags))
        if unseen:
            self.idb.must_register_streams(unseen)
        self.ddb.must_add_log_rows(lr)

    def must_add_columns(self, lc) -> None:
        """Columnar-batch twin of must_add_rows (LogColumns fast path)."""
        unseen = [(sid, tags) for sid, tags in lc.unique_streams()
                  if not self.idb.has_stream_id(sid)]
        if unseen:
            self.idb.must_register_streams(unseen)
        self.ddb.must_add_columns(lc)

    def debug_flush(self) -> None:
        self.idb.flush()
        self.ddb.flush_inmemory_parts()

    def force_merge(self) -> None:
        self.ddb.force_merge()

    def stats(self) -> dict:
        s = self.ddb.stats()
        s["streams"] = self.idb.num_streams()
        return s

    def close(self) -> None:
        self.ddb.close()
        self.idb.close()
