"""Columnar block builder.

A block holds up to MAX_ROWS_PER_BLOCK rows of a *single* stream, sorted by
timestamp (reference: blocks are per-streamID with sorted timestamps —
lib/logstorage/block.go:15-24, blockHeader records one streamID —
block_header.go:17-41).  Per-block, every present field becomes a column
encoded via the values encoder; columns whose value is identical across all
rows become const columns (block.go:109-124); non-const/dict columns get a
token bloom filter (block.go:134-175).

Limits follow consts.go:21-30: 8M rows hard cap; we chunk at TPU-friendlier
targets (128Ki rows / 2MB uncompressed) so a block maps to one device staging
unit.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np

from ..utils.hashing import hash_tokens
from ..utils.tokenizer import tokenize_arena, tokenize_string, unique_tokens_bytes
from .bloom import bloom_build
from .log_rows import StreamID
from .values_encoder import (EncodedColumn, VT_CONST, VT_DICT, VT_STRING,
                             encode_values)

MAX_ROWS_PER_BLOCK = 128 * 1024
# 8MB (vs the reference's 2MB — consts.go:21-30): bigger blocks amortize
# per-dispatch overhead when a block is one TPU staging unit
MAX_UNCOMPRESSED_BLOCK_SIZE = 8 << 20
MAX_COLUMNS_PER_BLOCK = 2000


@dataclass
class BlockData:
    """One decoded columnar block (in-memory or read from a part)."""

    stream_id: StreamID
    timestamps: np.ndarray                      # int64[R] ns, sorted
    columns: list[EncodedColumn]                # per-row columns
    const_columns: list[tuple[str, str]]        # (name, value)
    stream_tags_str: str = ""                   # canonical {k="v"} labels

    @property
    def num_rows(self) -> int:
        return int(self.timestamps.shape[0])

    @property
    def min_ts(self) -> int:
        return int(self.timestamps[0]) if self.num_rows else 0

    @property
    def max_ts(self) -> int:
        return int(self.timestamps[-1]) if self.num_rows else 0

    def get_column(self, name: str) -> EncodedColumn | None:
        for c in self.columns:
            if c.name == name:
                return c
        return None

    def get_const(self, name: str) -> str | None:
        for k, v in self.const_columns:
            if k == name:
                return v
        return None

    def uncompressed_size(self) -> int:
        sz = 8 * self.num_rows
        for c in self.columns:
            if c.vtype == VT_STRING:
                sz += int(c.lengths.sum()) + 8 * self.num_rows
            elif c.vtype == VT_DICT:
                sz += self.num_rows
            else:
                sz += c.nums.itemsize * self.num_rows
        return sz


def column_token_hashes(col: EncodedColumn, nrows: int):
    """Distinct token hashes of a column, or None for const/dict
    columns (no token coverage).  The block builder feeds these to the
    bloom; the seal-time filter-index build (storage/filterindex) calls
    this again for merge pass-through columns read back from disk —
    tokenization is deterministic and VT round-trips are exact, so the
    recomputed set equals the one the bloom was built from."""
    if col.vtype in (VT_CONST, VT_DICT):
        return None
    if col.vtype == VT_STRING:
        # native fast path: tokenize+hash+dedupe in one C++ pass
        from .. import native
        hashes = native.unique_token_hashes_native(
            col.arena, col.offsets, col.lengths)
        if hashes is not None:
            return hashes
        ts_, te_, _ = tokenize_arena(col.arena, col.offsets, col.lengths)
        tokens = unique_tokens_bytes(col.arena, ts_, te_)
    else:
        seen: set[str] = set()
        tokens = []
        for v in col.to_strings(nrows):
            for t in tokenize_string(v):
                if t not in seen:
                    seen.add(t)
                    tokens.append(t)
    return hash_tokens(tokens)


def build_column_bloom(col: EncodedColumn, nrows: int) -> None:
    """Attach a token bloom filter to a column (skipped for const/dict)."""
    hashes = column_token_hashes(col, nrows)
    if hashes is None:
        return
    col.token_hashes = hashes
    col.bloom = bloom_build(hashes)


def row_cost_cum(rows: list[list[tuple[str, str]]]) -> np.ndarray:
    """Inclusive running total of per-row encoded-size estimates for
    tuple-list rows: len(k)+len(v)+16 per field plus 8 per row — the
    same accounting the columnar path reaches by summing per-column
    value lengths + (len(name)+16) per column."""
    return np.cumsum(np.fromiter(
        (sum(len(k) + len(v) for k, v in r) + 16 * len(r) + 8
         for r in rows), dtype=np.int64, count=len(rows)))


def chunk_end(cum: np.ndarray, start: int,
              max_rows: int = MAX_ROWS_PER_BLOCK,
              max_bytes: int = MAX_UNCOMPRESSED_BLOCK_SIZE) -> int:
    """End (exclusive) of the size-bounded block chunk starting at
    `start`, given the inclusive cumsum of per-row size estimates.

    A row joins while the byte budget before it is still positive
    (strict `<`), with at least one row per chunk and at most
    `max_rows`.  This is THE single chunking rule: the row path here
    and the columnar path (storage/block_build) used to carry separate
    copies that disagreed when a row landed exactly on the byte
    boundary."""
    n = int(cum.shape[0])
    base = int(cum[start - 1]) if start else 0
    e = start + 1 + int(np.searchsorted(cum[start:], base + max_bytes,
                                        side="left"))
    return min(e, start + max_rows, n)


def build_blocks(
    stream_id: StreamID,
    timestamps: np.ndarray,
    rows: list[list[tuple[str, str]]],
    stream_tags_str: str = "",
    max_rows: int = MAX_ROWS_PER_BLOCK,
    max_bytes: int = MAX_UNCOMPRESSED_BLOCK_SIZE,
) -> list[BlockData]:
    """Build columnar blocks from time-sorted rows of one stream."""
    out: list[BlockData] = []
    n = len(rows)
    if n == 0:
        return out
    cum = row_cost_cum(rows)
    i = 0
    while i < n:
        j = chunk_end(cum, i, max_rows, max_bytes)
        out.append(_build_one_block(stream_id, timestamps[i:j], rows[i:j],
                                    stream_tags_str))
        i = j
    return out


def _build_one_block(
    stream_id: StreamID,
    timestamps: np.ndarray,
    rows: list[list[tuple[str, str]]],
    stream_tags_str: str,
) -> BlockData:
    nrows = len(rows)
    # same-fields fast path (reference block.go:224-244): most batches from a
    # single source share one field schema, so detect it cheaply first
    names: list[str] = [k for k, _ in rows[0]]
    same_schema = True
    for r in rows[1:]:
        if len(r) != len(names) or any(r[i][0] != names[i]
                                       for i in range(len(names))):
            same_schema = False
            break

    col_values: dict[str, list[str]] = {}
    if same_schema:
        for idx, name in enumerate(names):
            if name not in col_values:
                col_values[name] = [r[idx][1] for r in rows]
    else:
        all_names: dict[str, None] = {}
        for r in rows:
            for k, _ in r:
                all_names.setdefault(k, None)
        for name in all_names:
            col_values[name] = [""] * nrows
        for ri, r in enumerate(rows):
            for k, v in r:
                col_values[k][ri] = v

    return build_block_from_columns(stream_id, timestamps, col_values,
                                    stream_tags_str)


def build_block_from_columns(
    stream_id: StreamID,
    timestamps: np.ndarray,
    col_values: dict[str, list[str]],
    stream_tags_str: str = "",
) -> BlockData:
    """Encode one block from column-oriented values (the columnar fast path
    used by the streaming merger — no per-row tuples anywhere)."""
    ts = np.asarray(timestamps, dtype=np.int64)
    nrows = int(ts.shape[0])
    columns: list[EncodedColumn] = []
    const_columns: list[tuple[str, str]] = []
    for name, values in col_values.items():
        assert len(values) == nrows
        col = encode_values(name, values)
        if col.vtype == VT_CONST:
            const_columns.append((name, col.const_value))
        else:
            build_column_bloom(col, nrows)
            columns.append(col)

    # timestamps must be sorted within a block (reference asserts this:
    # block.go:177-195)
    return BlockData(stream_id=stream_id, timestamps=ts, columns=columns,
                     const_columns=const_columns,
                     stream_tags_str=stream_tags_str)


def blocks_from_log_rows(lr) -> list[BlockData]:
    """Sort a LogRows batch by (stream_id, timestamp) and build blocks.

    Reference: datadb flush sorts rows the same way before building an
    in-memory part (datadb.go:749-763).  The planning + encoding body
    lives in storage/block_build so a DataDB can run the independent
    (stream, chunk) tasks on its build pool; this serial entry point is
    kept for callers without one.
    """
    from .block_build import build_log_rows_blocks
    return build_log_rows_blocks(lr)
