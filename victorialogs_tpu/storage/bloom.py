"""Per-block split bloom filters over column tokens.

Same parameters as the reference (lib/logstorage/bloomfilter.go:15-19):
6 probe bits per token, 16 bits allotted per distinct token, one filter per
(block, column).  Probe positions are derived from the token's xxhash64 by an
iterated splitmix64 stream (the reference iterates xxhash on the hash —
bloomfilter.go:126-170; splitmix keeps the derivation pure integer math so the
same positions are computable on device from a staged uint64 hash without any
string access).

Build and probe are fully vectorized over numpy uint64 words.  The device-side
probe (tpu/) consumes the same words reinterpreted as 2× uint32 lanes.
"""

from __future__ import annotations

import numpy as np

from ..utils.hashing import splitmix64_np

BLOOM_HASHES = 6
BLOOM_BITS_PER_TOKEN = 16


def bloom_num_words(ntokens: int) -> int:
    bits = max(64, BLOOM_BITS_PER_TOKEN * ntokens)
    return (bits + 63) // 64


def bloom_build(token_hashes: np.ndarray) -> np.ndarray:
    """Build a bloom filter from uint64 token hashes -> uint64[W] words."""
    nwords = bloom_num_words(len(token_hashes))
    nbits = np.uint64(nwords * 64)
    words = np.zeros(nwords, dtype=np.uint64)
    h = token_hashes.astype(np.uint64, copy=True)
    one = np.uint64(1)
    for _ in range(BLOOM_HASHES):
        pos = h % nbits
        np.bitwise_or.at(words, (pos >> np.uint64(6)).astype(np.int64),
                         one << (pos & np.uint64(63)))
        h = splitmix64_np(h)
    return words


def bloom_contains_all(words: np.ndarray, token_hashes: np.ndarray) -> bool:
    """True if every token's 6 probe bits are set (possible false positives)."""
    if len(token_hashes) == 0:
        return True
    nbits = np.uint64(words.shape[0] * 64)
    h = token_hashes.astype(np.uint64, copy=True)
    one = np.uint64(1)
    ok = np.ones(len(h), dtype=bool)
    for _ in range(BLOOM_HASHES):
        pos = h % nbits
        bit = (words[(pos >> np.uint64(6)).astype(np.int64)]
               >> (pos & np.uint64(63))) & one
        ok &= bit.astype(bool)
        if not ok.any():
            return False
        h = splitmix64_np(h)
    return bool(ok.all())


def bloom_probe_positions(token_hashes: np.ndarray, nwords: int) -> np.ndarray:
    """All probe bit positions for the given hashes -> uint64[T, 6].

    Used by the TPU path: positions are computed host-side for the (few) query
    tokens, the device only tests bits across many block blooms at once.
    """
    nbits = np.uint64(nwords * 64)
    h = token_hashes.astype(np.uint64, copy=True)
    out = np.empty((len(h), BLOOM_HASHES), dtype=np.uint64)
    for k in range(BLOOM_HASHES):
        out[:, k] = h % nbits
        h = splitmix64_np(h)
    return out
