"""Per-block split bloom filters over column tokens.

Same parameters as the reference (lib/logstorage/bloomfilter.go:15-19):
6 probe bits per token, 16 bits allotted per distinct token, one filter per
(block, column).  Probe positions are derived from the token's xxhash64 by an
iterated splitmix64 stream (the reference iterates xxhash on the hash —
bloomfilter.go:126-170; splitmix keeps the derivation pure integer math so the
same positions are computable on device from a staged uint64 hash without any
string access).

Build and probe are fully vectorized over numpy uint64 words.  The device-side
probe (tpu/) consumes the same words reinterpreted as 2× uint32 lanes.
"""

from __future__ import annotations

import numpy as np

from ..utils.hashing import splitmix64_np

BLOOM_HASHES = 6
BLOOM_BITS_PER_TOKEN = 16


def bloom_num_words(ntokens: int) -> int:
    bits = max(64, BLOOM_BITS_PER_TOKEN * ntokens)
    return (bits + 63) // 64


def bloom_build(token_hashes: np.ndarray) -> np.ndarray:
    """Build a bloom filter from uint64 token hashes -> uint64[W] words."""
    nwords = bloom_num_words(len(token_hashes))
    nbits = np.uint64(nwords * 64)
    words = np.zeros(nwords, dtype=np.uint64)
    h = token_hashes.astype(np.uint64, copy=True)
    one = np.uint64(1)
    for _ in range(BLOOM_HASHES):
        pos = h % nbits
        np.bitwise_or.at(words, (pos >> np.uint64(6)).astype(np.int64),
                         one << (pos & np.uint64(63)))
        h = splitmix64_np(h)
    return words


def bloom_contains_all(words: np.ndarray, token_hashes: np.ndarray) -> bool:
    """True if every token's 6 probe bits are set (possible false positives)."""
    if len(token_hashes) == 0:
        return True
    nbits = np.uint64(words.shape[0] * 64)
    h = token_hashes.astype(np.uint64, copy=True)
    one = np.uint64(1)
    ok = np.ones(len(h), dtype=bool)
    for _ in range(BLOOM_HASHES):
        pos = h % nbits
        bit = (words[(pos >> np.uint64(6)).astype(np.int64)]
               >> (pos & np.uint64(63))) & one
        ok &= bit.astype(bool)
        if not ok.any():
            return False
        h = splitmix64_np(h)
    return bool(ok.all())


def bloom_probe_positions(token_hashes: np.ndarray, nwords: int) -> np.ndarray:
    """All probe bit positions for the given hashes -> uint64[T, 6].

    The host side of the batched probe: positions are computed once per
    distinct filter word-count for the (few) query tokens, then tested
    against MANY block filters at once — the packed plane and aggregate
    probes in storage/filterbank.py and the device keep-mask in
    tpu/bloom_device.py all consume these positions.  The iteration must
    stay in lockstep with bloom_contains_all's splitmix64 stream
    (pinned by tests/test_filterbank.py) or host and device pruning
    would drift.
    """
    return bloom_probe_positions_multi(token_hashes, (nwords,))[0]


def bloom_probe_positions_multi(token_hashes: np.ndarray,
                                nwords_list) -> np.ndarray:
    """Probe positions for SEVERAL filter sizes at once -> uint64[S, T, 6].

    A part's blocks carry different-size filters (word count tracks the
    block's distinct token count), so batched probing needs positions
    per distinct size; the splitmix64 stream depends only on the hashes
    and is iterated once here, then reduced modulo each size's bit
    count — a single broadcast instead of S separate iterations."""
    h = token_hashes.astype(np.uint64, copy=True)
    hs = np.empty((len(h), BLOOM_HASHES), dtype=np.uint64)
    for k in range(BLOOM_HASHES):
        hs[:, k] = h
        h = splitmix64_np(h)
    nbits = np.asarray(nwords_list, dtype=np.uint64) * np.uint64(64)
    return hs[None, :, :] % nbits[:, None, None]
