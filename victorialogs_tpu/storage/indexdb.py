"""Per-partition stream index.

Indexes *stream labels only* (never message content), like the reference's
mergeset-backed indexdb (lib/logstorage/indexdb.go:20-31): it answers
"which streamIDs in this partition match `{label=...}`" and "what are the
tags of streamID X".

The reference stores three key namespaces in an LSM mergeset table —
streamID registry, streamID->tags, and (tag,value)->streamIDs posting lists
(indexdb.go:20-31, 182-307).  This implementation keeps the same namespaces
in a MULTI-LEVEL structure shaped like a mergeset table
(vendor/.../lib/mergeset/table.go: sorted immutable parts + background
merges):

- immutable columnar SNAPSHOT FILES (`streams.snap.NNNNNN` —
  stream_snapshot.py): sorted numpy arrays with binary-searched registry
  lookups and lazy per-(label,value) posting materialization.  A manifest
  (`streams.parts.json`) lists the live files; reopen is a bulk load.
- a mutable TAIL: streams registered since the last flush, held in dicts/
  sets, backed by the append-only `streams.jsonl` log (fsynced before
  rows become durable — the register-before-rows invariant partition.py
  relies on).
- the tail FLUSHES to a new small snapshot file when it grows past
  COMPACT_TAIL_STREAMS (bounding tail RAM) — an O(tail) write that never
  rewrites existing files, unlike the r3/r4 single-snapshot design whose
  per-flush base rewrite cost O(total) (the ~2x write-amp cliff the r4
  verdict flagged).
- BACKGROUND MERGES bound read fanout: when the file count exceeds
  MAX_SNAPSHOTS, the MERGE_BATCH smallest files k-way-merge (array-level,
  stream_snapshot.merge_snapshots) into one.  Write amplification is
  O(levels), not O(n/tail): ~1.0x until the first merge triggers, ~1.3x
  at 10M streams (tools/bench_indexdb.py records it).

Crash safety: snapshot files and the manifest write tmp+fsync+rename;
reopen replays only the log tail past the contiguous-healthy snapshot
coverage, and files absent from the manifest (crashed merges) are swept.

Query results are memoized in the two-generation filter cache
(indexdb.go:55-57), invalidated on registrations.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

from .log_rows import StreamID, TenantID
from .stream_filter import StreamFilter, _compiled, parse_stream_tags
from .stream_snapshot import (StreamSnapshot, merge_snapshots,
                              write_snapshot)

STREAMS_FILENAME = "streams.jsonl"
SNAPSHOT_FILENAME = "streams.snap"          # legacy single-file name
MANIFEST_FILENAME = "streams.parts.json"

# flush the replayed/accumulated tail to a snapshot file past this size
SNAPSHOT_MIN_TAIL = 10_000
# flush a LIVE index's tail once it reaches this size: bounds tail RAM
# (~1KB/stream of Python dict+set structure) regardless of daily stream
# cardinality; the snapshot side is ~100B/stream of numpy
COMPACT_TAIL_STREAMS = 250_000
# merge the MERGE_BATCH smallest snapshot files once more than
# MAX_SNAPSHOTS exist: bounds read fanout (membership probes and posting
# unions walk every level) while keeping write amplification ~1+1/3
MAX_SNAPSHOTS = 32
MERGE_BATCH = 10


class IndexDB:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        # ---- tail (post-flush registrations) ----
        self._streams: dict[StreamID, str] = {}
        self._by_tenant: dict[TenantID, list[StreamID]] = {}
        self._postings: dict[TenantID, dict[str, dict[str, set]]] = {}
        self._label_any: dict[TenantID, dict[str, set]] = {}
        from ..utils.cache import TwoGenCache
        self._filter_cache = TwoGenCache()
        # bumped on every registration and snapshot swap: queries that
        # evaluated against an older generation must not poison the cache
        self._gen = 0
        self._file_path = os.path.join(path, STREAMS_FILENAME)
        self._manifest_path = os.path.join(path, MANIFEST_FILENAME)
        # ---- observability (tools/bench_indexdb.py) ----
        self.snap_bytes_written = 0
        self.snap_files_written = 0
        self.merge_count = 0
        # ---- snapshot levels ----
        self._snaps: list[StreamSnapshot] = []      # oldest -> newest
        self._snap_files: list[str] = []            # parallel to _snaps
        self._snap_seq = 0
        replay_from = self._load_levels()
        if os.path.exists(self._file_path):
            if replay_from > os.path.getsize(self._file_path):
                # log shrank behind the snapshots (manual tampering):
                # distrust every snapshot level
                self._drop_all_levels()
                replay_from = 0
            self._load(replay_from)
            # crash repair: a torn final line (no trailing newline) would
            # otherwise MERGE with the first post-crash append, silently
            # losing that registration on the next reopen
            with open(self._file_path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size:
                    f.seek(size - 1)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
        self._file = open(self._file_path, "a", buffering=1 << 16)
        self._compact_thread: threading.Thread | None = None
        self._compact_backoff_until = 0.0
        self._compact_error: str | None = None
        if len(self._streams) >= SNAPSHOT_MIN_TAIL:
            # pay the flush once now so every later open is a bulk load
            self._flush_tail_locked()

    # ---- level loading / manifest ----
    def _load_levels(self) -> int:
        """Load snapshot files per the manifest; returns the log offset to
        replay from (coverage of the contiguous healthy prefix — a torn
        middle file forces replay from before it; later healthy files
        stay loaded and dedupe the replay)."""
        files: list[str] = []
        if os.path.exists(self._manifest_path):
            try:
                with open(self._manifest_path) as f:
                    files = json.load(f)["files"]
            except (OSError, ValueError, KeyError, TypeError):
                files = []  # unreadable/torn manifest: full log replay
        elif os.path.exists(os.path.join(self.path, SNAPSHOT_FILENAME)):
            files = [SNAPSHOT_FILENAME]          # pre-manifest layout
        loaded: list[tuple[str, StreamSnapshot | None]] = []
        manifest_dirty = False
        for fn in files:
            p = os.path.join(self.path, fn)
            try:
                loaded.append((fn, StreamSnapshot(p)))
            # vlint: allow-broad-except(any parse error means torn file)
            except Exception:
                loaded.append((fn, None))        # torn file
                manifest_dirty = True
        # order by log coverage (torn files first, forcing replay of the
        # whole log); replay starts at the last offset of the contiguous
        # healthy prefix — later healthy files stay loaded and dedupe
        # the replayed records
        loaded.sort(key=lambda t: t[1].log_offset if t[1] else -1)
        replay_from = 0
        healthy_prefix = True
        for fn, snap in loaded:
            if snap is None:
                healthy_prefix = False
                continue
            if healthy_prefix:
                replay_from = max(replay_from, snap.log_offset)
            self._snaps.append(snap)
            self._snap_files.append(fn)
        # sweep stale snapshot files a crashed merge left behind
        live = set(self._snap_files)
        for fn in os.listdir(self.path):
            if (fn.startswith(SNAPSHOT_FILENAME) and fn not in live) or \
                    fn.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.path, fn))
                except OSError:
                    pass
        for fn in self._snap_files:
            if fn.startswith(SNAPSHOT_FILENAME + "."):
                try:
                    self._snap_seq = max(self._snap_seq,
                                         int(fn.rsplit(".", 1)[1]) + 1)
                except ValueError:
                    pass
        if manifest_dirty:
            # drop torn entries now, or every later open would treat the
            # missing file as torn and re-pay a full log replay
            self._write_manifest()
        return replay_from

    def _drop_all_levels(self) -> None:
        for fn in self._snap_files:
            try:
                os.remove(os.path.join(self.path, fn))
            except OSError:
                pass
        self._snaps.clear()
        self._snap_files.clear()
        self._write_manifest()

    # vlint: allow-lock-blocking-call(manifest swap atomic with level swap)
    def _write_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"files": self._snap_files}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)

    def _next_snap_file(self) -> str:
        fn = f"{SNAPSHOT_FILENAME}.{self._snap_seq:06d}"
        self._snap_seq += 1
        return fn

    def _load(self, offset: int) -> None:
        with open(self._file_path) as f:
            if offset:
                f.seek(offset)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write after crash: ignore
                sid = StreamID(TenantID(rec["a"], rec["p"]),
                               rec["h"], rec["l"])
                if any(s.find(sid) >= 0 for s in reversed(self._snaps)):
                    continue
                self._register_mem(sid, rec["t"])

    def _register_mem(self, sid: StreamID, tags_str: str) -> None:
        if sid in self._streams:
            return
        self._streams[sid] = tags_str
        self._by_tenant.setdefault(sid.tenant, []).append(sid)
        postings = self._postings.setdefault(sid.tenant, {})
        label_any = self._label_any.setdefault(sid.tenant, {})
        for label, value in parse_stream_tags(tags_str).items():
            postings.setdefault(label, {}).setdefault(value, set()).add(sid)
            label_any.setdefault(label, set()).add(sid)

    # ---- tail flush + background merge ----
    # vlint: allow-lock-blocking-call(durability-ordered tail flush)
    def _flush_tail_locked(self) -> None:
        """Write the tail as a NEW snapshot level (O(tail); existing
        files untouched), swap it in, clear the tail."""
        self._file.flush()
        log_size = os.path.getsize(self._file_path) \
            if os.path.exists(self._file_path) else 0
        fn = self._next_snap_file()
        p = os.path.join(self.path, fn)
        write_snapshot(p, dict(self._streams), log_size)
        self._account_write_locked(p)
        self._snaps.append(StreamSnapshot(p))
        self._snap_files.append(fn)
        self._write_manifest()
        self._streams.clear()
        self._by_tenant.clear()
        self._postings.clear()
        self._label_any.clear()
        self._filter_cache.clear()
        self._gen += 1

    def _account_write_locked(self, path: str) -> None:
        # caller holds self._lock: the compaction thread and foreground
        # flushes both account here, and unlocked `+=` loses updates
        self.snap_bytes_written += os.path.getsize(path)
        self.snap_files_written += 1

    # vlint: allow-lock-blocking-call(log fsync before freeze, durability)
    def _maybe_compact_async(self) -> None:
        """Kick off a background tail flush (and, when the level count
        passed MAX_SNAPSHOTS, a k-way merge of the smallest levels).

        The mergeset background-merge analogue: the frozen tail writes a
        new level OUTSIDE the lock (ingest and queries continue against
        the old levels), then levels swap under the lock."""
        if self._compact_thread is not None and \
                self._compact_thread.is_alive():
            return
        import time
        if time.monotonic() < self._compact_backoff_until:
            return
        frozen = dict(self._streams)
        self._file.flush()
        os.fsync(self._file.fileno())
        log_size = os.path.getsize(self._file_path)

        def work():
            try:
                with self._lock:
                    fn = self._next_snap_file()
                p = os.path.join(self.path, fn)
                write_snapshot(p, frozen, log_size)
                new_snap = StreamSnapshot(p)
            # any write failure (disk full, permissions, serialization)
            # must back off, not kill the compaction thread; the error
            # is kept in _compact_error
            # vlint: allow-broad-except(backoff keeps compactor alive)
            except Exception as e:
                # disk full / permissions: keep serving from the old
                # levels, back off so registrations don't re-pay a
                # flush per batch just to fail again
                import time
                with self._lock:
                    self._compact_backoff_until = time.monotonic() + 60.0
                    self._compact_error = repr(e)
                return
            with self._lock:
                self._account_write_locked(p)
                self._snaps.append(new_snap)
                self._snap_files.append(fn)
                self._write_manifest()
                self._gen += 1
                remaining = {sid: tags
                             for sid, tags in self._streams.items()
                             if sid not in frozen}
                self._streams.clear()
                self._by_tenant.clear()
                self._postings.clear()
                self._label_any.clear()
                for sid, tags in remaining.items():
                    self._register_mem(sid, tags)
                self._filter_cache.clear()
            self._merge_levels_if_needed()

        self._compact_thread = threading.Thread(
            target=work, daemon=True, name="vl-idx-compact")
        self._compact_thread.start()

    def _merge_levels_if_needed(self) -> None:
        """k-way merge of the MERGE_BATCH smallest levels once more than
        MAX_SNAPSHOTS exist.  Runs on the compaction thread; sources are
        immutable, so only the swap takes the lock."""
        while True:
            with self._lock:
                if len(self._snaps) <= MAX_SNAPSHOTS:
                    return
                order = sorted(range(len(self._snaps)),
                               key=lambda i: self._snaps[i].n)
                pick = sorted(order[:MERGE_BATCH])
                srcs = [self._snaps[i] for i in pick]
                src_files = [self._snap_files[i] for i in pick]
                fn = self._next_snap_file()
            p = os.path.join(self.path, fn)
            try:
                merge_snapshots(p, srcs,
                                max(s.log_offset for s in srcs))
                merged = StreamSnapshot(p)
            # vlint: allow-broad-except(backoff keeps compactor alive)
            except Exception as e:
                import time
                with self._lock:
                    self._compact_backoff_until = time.monotonic() + 60.0
                    self._compact_error = repr(e)
                return
            with self._lock:
                self._account_write_locked(p)
                # replace the sources BY NAME: a concurrent tail flush
                # may have appended levels since the pick — they must
                # survive the swap
                gone = set(src_files)
                keep = [i for i, f in enumerate(self._snap_files)
                        if f not in gone]
                self._snaps = [self._snaps[i] for i in keep] + [merged]
                self._snap_files = [self._snap_files[i]
                                    for i in keep] + [fn]
                self._write_manifest()
                self.merge_count += 1
                self._gen += 1
                self._filter_cache.clear()
            for old in src_files:
                try:
                    os.remove(os.path.join(self.path, old))
                except OSError:
                    pass

    def force_merge(self) -> None:
        """Consolidate every level into one file (maintenance entry
        point; also what a final 'full compaction' would be)."""
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()
        with self._lock:
            if len(self._streams):
                self._flush_tail_locked()
            if len(self._snaps) <= 1:
                return
            srcs = list(self._snaps)
            src_files = list(self._snap_files)
            fn = self._next_snap_file()
        p = os.path.join(self.path, fn)
        merge_snapshots(p, srcs, max(s.log_offset for s in srcs))
        merged = StreamSnapshot(p)
        with self._lock:
            self._account_write_locked(p)
            # a background flush may have appended a level since the
            # capture — replace only the merged sources, keep the rest
            gone = set(src_files)
            keep = [i for i, f in enumerate(self._snap_files)
                    if f not in gone]
            self._snaps = [self._snaps[i] for i in keep] + [merged]
            self._snap_files = [self._snap_files[i] for i in keep] + [fn]
            self._write_manifest()
            self.merge_count += 1
            self._gen += 1
            self._filter_cache.clear()
        for old in src_files:
            try:
                os.remove(os.path.join(self.path, old))
            except OSError:
                pass

    # vlint: allow-lock-blocking-call(shutdown: final flush under lock)
    def close(self) -> None:
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()
        with self._lock:
            self._file.flush()
            self._file.close()
            if len(self._streams) >= SNAPSHOT_MIN_TAIL:
                log_size = os.path.getsize(self._file_path)
                fn = self._next_snap_file()
                p = os.path.join(self.path, fn)
                write_snapshot(p, dict(self._streams), log_size)
                self._account_write_locked(p)
                self._snap_files.append(fn)
                self._snaps.append(StreamSnapshot(p))
                self._write_manifest()
                # the flushed tail now lives in the level — clear it so
                # post-close reads (metrics scrapes) don't double-count
                self._streams.clear()
                self._by_tenant.clear()
                self._postings.clear()
                self._label_any.clear()

    # vlint: allow-lock-blocking-call(explicit durability barrier)
    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    # ---- write path ----
    def has_stream_id(self, sid: StreamID) -> bool:
        with self._lock:
            return sid in self._streams or any(
                s.find(sid) >= 0 for s in reversed(self._snaps))

    def must_register_stream(self, sid: StreamID, tags_str: str) -> None:
        self.must_register_streams([(sid, tags_str)])

    # vlint: allow-lock-blocking-call(register-before-rows fsync invariant)
    def must_register_streams(
            self, streams: list[tuple[StreamID, str]]) -> None:
        """Durably register new streams (fsynced before returning, so rows
        that reach a durable part can never reference an unindexed stream —
        the register-before-rows invariant partition.py relies on).

        Membership against the snapshot levels is batched per tenant
        (StreamSnapshot.contains_batch) so the hot re-registration path
        stays vectorized no matter how many levels exist."""
        with self._lock:
            cand = [(sid, tags) for sid, tags in streams
                    if sid not in self._streams]
            if cand and self._snaps:
                by_tenant: dict[TenantID, list[int]] = {}
                for k, (sid, _t) in enumerate(cand):
                    by_tenant.setdefault(sid.tenant, []).append(k)
                known = np.zeros(len(cand), dtype=bool)
                for tenant, idxs in by_tenant.items():
                    hi = np.fromiter((cand[k][0].hi for k in idxs),
                                     dtype=np.uint64, count=len(idxs))
                    lo = np.fromiter((cand[k][0].lo for k in idxs),
                                     dtype=np.uint64, count=len(idxs))
                    mask = np.zeros(len(idxs), dtype=bool)
                    for s in reversed(self._snaps):
                        todo = ~mask
                        if not todo.any():
                            break
                        mask |= s.contains_batch(tenant, hi, lo)
                    for j, k in enumerate(idxs):
                        if mask[j]:
                            known[k] = True
                cand = [c for k, c in enumerate(cand) if not known[k]]
            wrote = False
            seen_batch: set = set()
            for sid, tags_str in cand:
                if sid in seen_batch:
                    continue
                seen_batch.add(sid)
                self._register_mem(sid, tags_str)
                self._file.write(json.dumps({
                    "a": sid.tenant.account_id, "p": sid.tenant.project_id,
                    "h": sid.hi, "l": sid.lo, "t": tags_str,
                }, separators=(",", ":")) + "\n")
                wrote = True
            if wrote:
                self._file.flush()
                os.fsync(self._file.fileno())
                # registrations invalidate cached filter results
                self._filter_cache.clear()
                self._gen += 1
                if len(self._streams) >= COMPACT_TAIL_STREAMS:
                    self._maybe_compact_async()

    # ---- read path ----
    def get_stream_tags(self, sid: StreamID) -> str | None:
        with self._lock:
            got = self._streams.get(sid)
            if got is not None:
                return got
            for s in reversed(self._snaps):
                i = s.find(sid)
                if i >= 0:
                    return s.tags_at(i)
            return None

    def _match_tail(self, tenant: TenantID, tf, all_sids: set) -> set:
        """Tail-level match for ONE tag filter over the in-memory sets.

        Semantics match TagFilter.matches over tags.get(label, ""): absent
        labels read as the empty string, so negations and empty-matching
        regexes include label-less streams."""
        postings = self._postings.get(tenant, {}).get(tf.label, {})
        label_any = self._label_any.get(tenant, {}).get(tf.label, set())
        if tf.op == "=":
            if tf.value == "":
                return all_sids - label_any
            return set(postings.get(tf.value, ()))
        if tf.op == "!=":
            if tf.value == "":
                return set(label_any)
            return all_sids - postings.get(tf.value, set())
        rx = _compiled(tf.value)
        hit: set = set()
        for value, sids in postings.items():
            if rx.fullmatch(value) is not None:
                hit |= sids
        if rx.fullmatch("") is not None:
            hit |= all_sids - label_any
        if tf.op == "=~":
            return hit
        return all_sids - hit                      # '!~'

    @staticmethod
    def _match_snap(snap: StreamSnapshot, tenant: TenantID,
                    tf) -> "np.ndarray":
        """Snapshot-level match for ONE tag filter, entirely in sorted
        uint32 index space — StreamID objects materialize only for FINAL
        results (the mergeset analogue: binary-searched posting slices).
        Static over an explicit snapshot: it runs OUTSIDE the index lock
        (snapshots are immutable), so multi-second broad queries never
        stall ingestion."""
        s, e = snap.tenant_range(tenant)
        all_idx = None

        def universe():
            nonlocal all_idx
            if all_idx is None:
                all_idx = np.arange(s, e, dtype=np.uint32)
            return all_idx

        lp = snap.label_postings(tenant, tf.label)
        empty = np.empty(0, dtype=np.uint32)
        any_idx = lp.any_idx if lp is not None else empty
        if tf.op == "=":
            if tf.value == "":
                return np.setdiff1d(universe(), any_idx,
                                    assume_unique=True)
            return lp.lookup(tf.value) if lp is not None else empty
        if tf.op == "!=":
            if tf.value == "":
                return any_idx
            miss = lp.lookup(tf.value) if lp is not None else empty
            return np.setdiff1d(universe(), miss, assume_unique=True)
        rx = _compiled(tf.value)
        hits = []
        if lp is not None:
            for value, idxs in lp.items():
                if rx.fullmatch(value) is not None:
                    hits.append(idxs)
        hit = np.unique(np.concatenate(hits)) if hits else empty
        if rx.fullmatch("") is not None:
            hit = np.union1d(hit, np.setdiff1d(universe(), any_idx,
                                               assume_unique=True))
        if tf.op == "=~":
            return hit
        return np.setdiff1d(universe(), hit, assume_unique=True)  # '!~'

    def _tail_all(self, tenant: TenantID) -> set:
        return set(self._by_tenant.get(tenant, ()))

    def search_stream_ids(self, tenants: list[TenantID],
                          sf: StreamFilter) -> list[StreamID]:
        import heapq
        key = (tuple(tenants), sf)
        # phase 1 (locked): cache probe + TAIL evaluation (tail sets are
        # mutable but small — bounded by COMPACT_TAIL_STREAMS)
        with self._lock:
            cached = self._filter_cache.get(key)
            if cached is not None:
                return cached
            gen = self._gen
            snaps = list(self._snaps)
            result: set[StreamID] = set()
            for t in tenants:
                tail_all = self._tail_all(t)
                if not tail_all:
                    continue
                for grp in sf.or_groups:
                    ordered = self._ordered(grp)
                    cand: set | None = None
                    for tf in ordered:
                        m = self._match_tail(t, tf, tail_all)
                        cand = m if cand is None else cand & m
                        if not cand:
                            break
                    result |= cand if cand is not None else tail_all
        # phase 2 (UNLOCKED): per-level snapshot evaluation +
        # materialization — levels are immutable, so broad multi-second
        # queries never stall ingestion or other queries
        lists = [sorted(result)]
        for snap in snaps:
            snap_chunks: list = []
            for t in tenants:
                s, e = snap.tenant_range(t)
                if s == e:
                    continue
                for grp in sf.or_groups:
                    scand: np.ndarray | None = None
                    for tf in self._ordered(grp):
                        m = self._match_snap(snap, t, tf)
                        scand = m if scand is None else \
                            np.intersect1d(scand, m, assume_unique=True)
                        if not scand.size:
                            break
                    if scand is None:
                        scand = np.arange(s, e, dtype=np.uint32)
                    if scand.size:
                        snap_chunks.append(scand)
            if snap_chunks:
                # one sort per level; rows are stored sorted by
                # (tenant, hi, lo) — the same order StreamID sorts by —
                # so ascending indices materialize already sorted
                idxs = np.unique(np.concatenate(snap_chunks))
                lists.append(snap.streams_at(idxs))
        out = list(heapq.merge(*lists))
        with self._lock:
            if self._gen == gen:  # no registration/swap raced us
                self._filter_cache.put(key, out)
        return out

    @staticmethod
    def _ordered(grp):
        # '=' filters first: cheapest and most selective
        return sorted(grp, key=lambda tf: 0 if tf.op == "=" else
                      1 if tf.op == "=~" else 2)

    def all_stream_ids(self, tenants: list[TenantID]) -> list[StreamID]:
        with self._lock:
            snaps = list(self._snaps)
            out: list[StreamID] = []
            for t in tenants:
                out.extend(self._tail_all(t))
        # snapshot materialization outside the lock (immutable)
        for snap in snaps:
            for t in tenants:
                s, e = snap.tenant_range(t)
                if s != e:
                    out.extend(snap.streams_at(
                        np.arange(s, e, dtype=np.uint32)))
        out.sort()
        return out

    def num_streams(self) -> int:
        with self._lock:
            return len(self._streams) + sum(s.n for s in self._snaps)
