"""Per-partition stream index.

Indexes *stream labels only* (never message content), like the reference's
mergeset-backed indexdb (lib/logstorage/indexdb.go:20-31): it answers
"which streamIDs in this partition match `{label=...}`" and "what are the tags
of streamID X".

The reference stores three key namespaces in an LSM mergeset table —
streamID registry, streamID->tags, and (tag,value)->streamIDs posting lists
(indexdb.go:20-31, 182-307).  Our representation keeps all three: an
append-only registration log (`streams.jsonl`) hydrated at open into the
registry plus in-memory inverted postings, so `{app="x"}` resolves in
O(matching streams) via set intersection instead of re-parsing every
stream's tags.  Results are memoized in the filter cache (indexdb.go:55-57),
invalidated on registrations.
"""

from __future__ import annotations

import json
import os
import threading

from .log_rows import StreamID, TenantID
from .stream_filter import StreamFilter, _compiled, parse_stream_tags

STREAMS_FILENAME = "streams.jsonl"


class IndexDB:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        # streamID -> canonical tags string
        self._streams: dict[StreamID, str] = {}
        # tenant -> list[StreamID] for tenant-scoped scans
        self._by_tenant: dict[TenantID, list[StreamID]] = {}
        # inverted postings: tenant -> label -> value -> set[StreamID]
        # (the (tag,value)->streamIDs namespace — indexdb.go:20-31)
        self._postings: dict[TenantID, dict[str, dict[str, set]]] = {}
        # tenant -> label -> set[StreamID] having the label at all
        self._label_any: dict[TenantID, dict[str, set]] = {}
        # two-generation rotating result cache (reference cache.go:13-58,
        # filterStreamCache — indexdb.go:55-57)
        from ..utils.cache import TwoGenCache
        self._filter_cache = TwoGenCache()
        self._file_path = os.path.join(path, STREAMS_FILENAME)
        if os.path.exists(self._file_path):
            self._load()
        self._file = open(self._file_path, "a", buffering=1 << 16)

    def _load(self) -> None:
        with open(self._file_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write after crash: ignore
                sid = StreamID(TenantID(rec["a"], rec["p"]),
                               rec["h"], rec["l"])
                self._register_mem(sid, rec["t"])

    def _register_mem(self, sid: StreamID, tags_str: str) -> None:
        if sid in self._streams:
            return
        self._streams[sid] = tags_str
        self._by_tenant.setdefault(sid.tenant, []).append(sid)
        postings = self._postings.setdefault(sid.tenant, {})
        label_any = self._label_any.setdefault(sid.tenant, {})
        for label, value in parse_stream_tags(tags_str).items():
            postings.setdefault(label, {}).setdefault(value, set()).add(sid)
            label_any.setdefault(label, set()).add(sid)

    def close(self) -> None:
        with self._lock:
            self._file.flush()
            self._file.close()

    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    # ---- write path ----
    def has_stream_id(self, sid: StreamID) -> bool:
        with self._lock:
            return sid in self._streams

    def must_register_stream(self, sid: StreamID, tags_str: str) -> None:
        self.must_register_streams([(sid, tags_str)])

    def must_register_streams(
            self, streams: list[tuple[StreamID, str]]) -> None:
        """Durably register new streams (fsynced before returning, so rows
        that reach a durable part can never reference an unindexed stream —
        the register-before-rows invariant partition.py relies on)."""
        with self._lock:
            wrote = False
            for sid, tags_str in streams:
                if sid in self._streams:
                    continue
                self._register_mem(sid, tags_str)
                self._file.write(json.dumps({
                    "a": sid.tenant.account_id, "p": sid.tenant.project_id,
                    "h": sid.hi, "l": sid.lo, "t": tags_str,
                }, separators=(",", ":")) + "\n")
                wrote = True
            if wrote:
                self._file.flush()
                os.fsync(self._file.fileno())
                # registrations invalidate cached filter results
                self._filter_cache.clear()

    # ---- read path ----
    def get_stream_tags(self, sid: StreamID) -> str | None:
        with self._lock:
            return self._streams.get(sid)

    def _match_tag_filter(self, tenant: TenantID, tf, all_sids: set) -> set:
        """Exact stream set for ONE tag filter via the inverted postings.

        Semantics match TagFilter.matches over tags.get(label, ""): absent
        labels read as the empty string, so negations and empty-matching
        regexes include label-less streams."""
        postings = self._postings.get(tenant, {}).get(tf.label, {})
        label_any = self._label_any.get(tenant, {}).get(tf.label, set())
        if tf.op == "=":
            if tf.value == "":
                return all_sids - label_any
            return set(postings.get(tf.value, ()))
        if tf.op == "!=":
            if tf.value == "":
                return set(label_any)
            return all_sids - postings.get(tf.value, set())
        rx = _compiled(tf.value)
        hit: set = set()
        for value, sids in postings.items():
            if rx.fullmatch(value) is not None:
                hit |= sids
        if rx.fullmatch("") is not None:
            hit |= all_sids - label_any
        if tf.op == "=~":
            return hit
        return all_sids - hit                      # '!~'

    def search_stream_ids(self, tenants: list[TenantID],
                          sf: StreamFilter) -> list[StreamID]:
        key = (tuple(tenants), sf)
        with self._lock:
            cached = self._filter_cache.get(key)
            if cached is not None:
                return cached
            result: set[StreamID] = set()
            for t in tenants:
                all_sids = set(self._by_tenant.get(t, ()))
                if not all_sids:
                    continue
                for grp in sf.or_groups:
                    # '=' filters first: cheapest and most selective
                    ordered = sorted(
                        grp, key=lambda tf: 0 if tf.op == "=" else
                        1 if tf.op == "=~" else 2)
                    cand: set | None = None
                    for tf in ordered:
                        s = self._match_tag_filter(t, tf, all_sids)
                        cand = s if cand is None else cand & s
                        if not cand:
                            break
                    result |= cand if cand is not None else all_sids
            out = sorted(result)
            self._filter_cache.put(key, out)
            return out

    def all_stream_ids(self, tenants: list[TenantID]) -> list[StreamID]:
        with self._lock:
            out: list[StreamID] = []
            for t in tenants:
                out.extend(self._by_tenant.get(t, ()))
            out.sort()
            return out

    def num_streams(self) -> int:
        with self._lock:
            return len(self._streams)
