"""Per-partition stream index.

Indexes *stream labels only* (never message content), like the reference's
mergeset-backed indexdb (lib/logstorage/indexdb.go:20-31): it answers
"which streamIDs in this partition match `{label=...}`" and "what are the
tags of streamID X".

The reference stores three key namespaces in an LSM mergeset table —
streamID registry, streamID->tags, and (tag,value)->streamIDs posting lists
(indexdb.go:20-31, 182-307).  This implementation keeps the same namespaces
in a two-level structure shaped like a single-level mergeset:

- an immutable columnar SNAPSHOT (`streams.snap` — stream_snapshot.py):
  sorted numpy arrays with binary-searched registry lookups and lazy
  per-(label,value) posting materialization.  Reopen is a bulk load, not a
  replay; memory is tens of bytes per stream, not a Python set forest.
- a mutable TAIL: streams registered since the snapshot, held in dicts/
  sets exactly as before, backed by the append-only `streams.jsonl` log
  (fsynced before rows become durable — the register-before-rows
  invariant partition.py relies on).
- compaction merges snapshot+tail into a fresh snapshot at close (and
  after a reopen that replayed a large tail), the analogue of a mergeset
  background merge with the per-day partition lifecycle doing the
  scheduling.

Query results are memoized in the two-generation filter cache
(indexdb.go:55-57), invalidated on registrations.
"""

from __future__ import annotations

import json
import os
import threading

from .log_rows import StreamID, TenantID
from .stream_filter import StreamFilter, _compiled, parse_stream_tags
from .stream_snapshot import StreamSnapshot, compact_snapshot

STREAMS_FILENAME = "streams.jsonl"
SNAPSHOT_FILENAME = "streams.snap"

# compact when the replayed/accumulated tail exceeds this many streams
SNAPSHOT_MIN_TAIL = 10_000
# background-compact a LIVE index once its mutable tail reaches this size:
# bounds tail RAM (~1KB/stream of Python dict+set structure) regardless of
# daily stream cardinality; the snapshot side is ~100B/stream of numpy
COMPACT_TAIL_STREAMS = 250_000


class IndexDB:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        # ---- tail (post-snapshot registrations) ----
        self._streams: dict[StreamID, str] = {}
        self._by_tenant: dict[TenantID, list[StreamID]] = {}
        self._postings: dict[TenantID, dict[str, dict[str, set]]] = {}
        self._label_any: dict[TenantID, dict[str, set]] = {}
        from ..utils.cache import TwoGenCache
        self._filter_cache = TwoGenCache()
        # bumped on every registration and snapshot swap: queries that
        # evaluated against an older generation must not poison the cache
        self._gen = 0
        self._file_path = os.path.join(path, STREAMS_FILENAME)
        self._snap_path = os.path.join(path, SNAPSHOT_FILENAME)
        self._snap: StreamSnapshot | None = None
        if os.path.exists(self._snap_path):
            try:
                self._snap = StreamSnapshot(self._snap_path)
            except Exception:
                self._snap = None  # torn snapshot: full log replay below
        replay_from = self._snap.log_offset if self._snap is not None else 0
        if os.path.exists(self._file_path):
            if replay_from > os.path.getsize(self._file_path):
                # log shrank behind the snapshot (manual tampering):
                # distrust the snapshot entirely
                self._snap = None
                replay_from = 0
            self._load(replay_from)
            # crash repair: a torn final line (no trailing newline) would
            # otherwise MERGE with the first post-crash append, silently
            # losing that registration on the next reopen
            with open(self._file_path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size:
                    f.seek(size - 1)
                    if f.read(1) != b"\n":
                        f.write(b"\n")
        self._file = open(self._file_path, "a", buffering=1 << 16)
        self._compact_thread: threading.Thread | None = None
        self._compact_backoff_until = 0.0
        self._compact_error: str | None = None
        if len(self._streams) >= SNAPSHOT_MIN_TAIL:
            # pay compaction once now so every later open is a bulk load
            self._write_snapshot_locked()

    def _load(self, offset: int) -> None:
        with open(self._file_path) as f:
            if offset:
                f.seek(offset)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write after crash: ignore
                sid = StreamID(TenantID(rec["a"], rec["p"]),
                               rec["h"], rec["l"])
                if self._snap is not None and self._snap.find(sid) >= 0:
                    continue
                self._register_mem(sid, rec["t"])

    def _register_mem(self, sid: StreamID, tags_str: str) -> None:
        if sid in self._streams:
            return
        self._streams[sid] = tags_str
        self._by_tenant.setdefault(sid.tenant, []).append(sid)
        postings = self._postings.setdefault(sid.tenant, {})
        label_any = self._label_any.setdefault(sid.tenant, {})
        for label, value in parse_stream_tags(tags_str).items():
            postings.setdefault(label, {}).setdefault(value, set()).add(sid)
            label_any.setdefault(label, set()).add(sid)

    # ---- compaction ----
    def _write_snapshot_locked(self) -> None:
        self._file.flush()
        log_size = os.path.getsize(self._file_path) \
            if os.path.exists(self._file_path) else 0
        compact_snapshot(self._snap_path, self._snap,
                         dict(self._streams), log_size)
        self._snap = StreamSnapshot(self._snap_path)
        self._streams.clear()
        self._by_tenant.clear()
        self._postings.clear()
        self._label_any.clear()
        self._filter_cache.clear()

    def _maybe_compact_async(self) -> None:
        """Kick off a background compaction when the tail is large.

        The analogue of a mergeset background merge: a frozen copy of the
        tail merges with the current snapshot into a fresh snapshot file
        OUTSIDE the lock (ingest and queries continue against the old
        levels), then the levels swap under the lock."""
        if self._compact_thread is not None and \
                self._compact_thread.is_alive():
            return
        import time
        if time.monotonic() < self._compact_backoff_until:
            return
        frozen = dict(self._streams)
        old_snap = self._snap
        self._file.flush()
        os.fsync(self._file.fileno())
        log_size = os.path.getsize(self._file_path)

        def work():
            try:
                compact_snapshot(self._snap_path, old_snap, frozen,
                                 log_size)
                new_snap = StreamSnapshot(self._snap_path)
            except Exception as e:
                # disk full / permissions: keep serving from the old
                # levels, back off so registrations don't re-pay a full
                # merge per batch just to fail again
                import time
                with self._lock:
                    self._compact_backoff_until = time.monotonic() + 60.0
                    self._compact_error = repr(e)
                return
            with self._lock:
                self._snap = new_snap
                self._gen += 1
                remaining = {sid: tags
                             for sid, tags in self._streams.items()
                             if sid not in frozen}
                self._streams.clear()
                self._by_tenant.clear()
                self._postings.clear()
                self._label_any.clear()
                for sid, tags in remaining.items():
                    self._register_mem(sid, tags)
                self._filter_cache.clear()

        self._compact_thread = threading.Thread(
            target=work, daemon=True, name="vl-idx-compact")
        self._compact_thread.start()

    def close(self) -> None:
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()
        with self._lock:
            self._file.flush()
            self._file.close()
            if len(self._streams) >= SNAPSHOT_MIN_TAIL:
                log_size = os.path.getsize(self._file_path)
                compact_snapshot(self._snap_path, self._snap,
                                 dict(self._streams), log_size)

    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    # ---- write path ----
    def has_stream_id(self, sid: StreamID) -> bool:
        with self._lock:
            return sid in self._streams or (
                self._snap is not None and self._snap.find(sid) >= 0)

    def must_register_stream(self, sid: StreamID, tags_str: str) -> None:
        self.must_register_streams([(sid, tags_str)])

    def must_register_streams(
            self, streams: list[tuple[StreamID, str]]) -> None:
        """Durably register new streams (fsynced before returning, so rows
        that reach a durable part can never reference an unindexed stream —
        the register-before-rows invariant partition.py relies on)."""
        with self._lock:
            wrote = False
            for sid, tags_str in streams:
                if sid in self._streams or (
                        self._snap is not None and
                        self._snap.find(sid) >= 0):
                    continue
                self._register_mem(sid, tags_str)
                self._file.write(json.dumps({
                    "a": sid.tenant.account_id, "p": sid.tenant.project_id,
                    "h": sid.hi, "l": sid.lo, "t": tags_str,
                }, separators=(",", ":")) + "\n")
                wrote = True
            if wrote:
                self._file.flush()
                os.fsync(self._file.fileno())
                # registrations invalidate cached filter results
                self._filter_cache.clear()
                self._gen += 1
                if len(self._streams) >= COMPACT_TAIL_STREAMS:
                    self._maybe_compact_async()

    # ---- read path ----
    def get_stream_tags(self, sid: StreamID) -> str | None:
        with self._lock:
            got = self._streams.get(sid)
            if got is not None:
                return got
            if self._snap is not None:
                i = self._snap.find(sid)
                if i >= 0:
                    return self._snap.tags_at(i)
            return None

    def _match_tail(self, tenant: TenantID, tf, all_sids: set) -> set:
        """Tail-level match for ONE tag filter over the in-memory sets.

        Semantics match TagFilter.matches over tags.get(label, ""): absent
        labels read as the empty string, so negations and empty-matching
        regexes include label-less streams."""
        postings = self._postings.get(tenant, {}).get(tf.label, {})
        label_any = self._label_any.get(tenant, {}).get(tf.label, set())
        if tf.op == "=":
            if tf.value == "":
                return all_sids - label_any
            return set(postings.get(tf.value, ()))
        if tf.op == "!=":
            if tf.value == "":
                return set(label_any)
            return all_sids - postings.get(tf.value, set())
        rx = _compiled(tf.value)
        hit: set = set()
        for value, sids in postings.items():
            if rx.fullmatch(value) is not None:
                hit |= sids
        if rx.fullmatch("") is not None:
            hit |= all_sids - label_any
        if tf.op == "=~":
            return hit
        return all_sids - hit                      # '!~'

    @staticmethod
    def _match_snap(snap: StreamSnapshot, tenant: TenantID,
                    tf) -> "np.ndarray":
        """Snapshot-level match for ONE tag filter, entirely in sorted
        uint32 index space — StreamID objects materialize only for FINAL
        results (the mergeset analogue: binary-searched posting slices).
        Static over an explicit snapshot: it runs OUTSIDE the index lock
        (snapshots are immutable), so multi-second broad queries never
        stall ingestion."""
        import numpy as np
        s, e = snap.tenant_range(tenant)
        all_idx = None

        def universe():
            nonlocal all_idx
            if all_idx is None:
                all_idx = np.arange(s, e, dtype=np.uint32)
            return all_idx

        lp = snap.label_postings(tenant, tf.label)
        empty = np.empty(0, dtype=np.uint32)
        any_idx = lp.any_idx if lp is not None else empty
        if tf.op == "=":
            if tf.value == "":
                return np.setdiff1d(universe(), any_idx,
                                    assume_unique=True)
            return lp.lookup(tf.value) if lp is not None else empty
        if tf.op == "!=":
            if tf.value == "":
                return any_idx
            miss = lp.lookup(tf.value) if lp is not None else empty
            return np.setdiff1d(universe(), miss, assume_unique=True)
        rx = _compiled(tf.value)
        hits = []
        if lp is not None:
            for value, idxs in lp.items():
                if rx.fullmatch(value) is not None:
                    hits.append(idxs)
        hit = np.unique(np.concatenate(hits)) if hits else empty
        if rx.fullmatch("") is not None:
            hit = np.union1d(hit, np.setdiff1d(universe(), any_idx,
                                               assume_unique=True))
        if tf.op == "=~":
            return hit
        return np.setdiff1d(universe(), hit, assume_unique=True)  # '!~'

    def _tail_all(self, tenant: TenantID) -> set:
        return set(self._by_tenant.get(tenant, ()))

    def search_stream_ids(self, tenants: list[TenantID],
                          sf: StreamFilter) -> list[StreamID]:
        import heapq

        import numpy as np
        key = (tuple(tenants), sf)
        # phase 1 (locked): cache probe + TAIL evaluation (tail sets are
        # mutable but small — bounded by COMPACT_TAIL_STREAMS)
        with self._lock:
            cached = self._filter_cache.get(key)
            if cached is not None:
                return cached
            gen = self._gen
            snap = self._snap
            result: set[StreamID] = set()
            for t in tenants:
                tail_all = self._tail_all(t)
                if not tail_all:
                    continue
                for grp in sf.or_groups:
                    ordered = self._ordered(grp)
                    cand: set | None = None
                    for tf in ordered:
                        m = self._match_tail(t, tf, tail_all)
                        cand = m if cand is None else cand & m
                        if not cand:
                            break
                    result |= cand if cand is not None else tail_all
        # phase 2 (UNLOCKED): snapshot evaluation + materialization —
        # the snapshot is immutable, so broad multi-second queries never
        # stall ingestion or other queries
        snap_chunks: list = []
        if snap is not None:
            for t in tenants:
                s, e = snap.tenant_range(t)
                if s == e:
                    continue
                for grp in sf.or_groups:
                    scand: np.ndarray | None = None
                    for tf in self._ordered(grp):
                        m = self._match_snap(snap, t, tf)
                        scand = m if scand is None else \
                            np.intersect1d(scand, m, assume_unique=True)
                        if not scand.size:
                            break
                    if scand is None:
                        scand = np.arange(s, e, dtype=np.uint32)
                    if scand.size:
                        snap_chunks.append(scand)
        # one sort at the end instead of re-sorting per or-group/tenant
        snap_result = np.unique(np.concatenate(snap_chunks)) \
            if snap_chunks else np.empty(0, dtype=np.uint32)
        # snapshot rows are stored sorted by (tenant, hi, lo) — the same
        # order StreamID sorts by — so ascending indices are already
        # sorted; merge with the sorted tail instead of re-sorting
        snap_list = snap.streams_at(snap_result) if snap_result.size \
            else []
        out = list(heapq.merge(sorted(result), snap_list))
        with self._lock:
            if self._gen == gen:  # no registration/swap raced us
                self._filter_cache.put(key, out)
        return out

    @staticmethod
    def _ordered(grp):
        # '=' filters first: cheapest and most selective
        return sorted(grp, key=lambda tf: 0 if tf.op == "=" else
                      1 if tf.op == "=~" else 2)

    def all_stream_ids(self, tenants: list[TenantID]) -> list[StreamID]:
        import numpy as np
        with self._lock:
            snap = self._snap
            out: list[StreamID] = []
            for t in tenants:
                out.extend(self._tail_all(t))
        # snapshot materialization outside the lock (immutable)
        if snap is not None:
            for t in tenants:
                s, e = snap.tenant_range(t)
                if s != e:
                    out.extend(snap.streams_at(
                        np.arange(s, e, dtype=np.uint32)))
        out.sort()
        return out

    def num_streams(self) -> int:
        with self._lock:
            return len(self._streams) + \
                (self._snap.n if self._snap is not None else 0)
