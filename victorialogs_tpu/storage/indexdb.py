"""Per-partition stream index.

Indexes *stream labels only* (never message content), like the reference's
mergeset-backed indexdb (lib/logstorage/indexdb.go:20-31): it answers
"which streamIDs in this partition match `{label=...}`" and "what are the tags
of streamID X".

The reference stores three key namespaces in an LSM mergeset table.  Our v1
representation is an append-only registration log (`streams.jsonl.zst` frames)
hydrated into an in-memory table at open — same query semantics, with the
stream-filter result cache keyed by filter string (indexdb.go:55-57).  Stream
cardinality per day-partition is low relative to row count, so the in-memory
table is the right trade-off; a mergeset-equivalent SSTable backend can slot in
behind the same API.
"""

from __future__ import annotations

import json
import os
import threading

from .log_rows import StreamID, TenantID
from .stream_filter import StreamFilter, parse_stream_tags

STREAMS_FILENAME = "streams.jsonl"


class IndexDB:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        # streamID -> canonical tags string
        self._streams: dict[StreamID, str] = {}
        # tenant -> list[StreamID] for tenant-scoped scans
        self._by_tenant: dict[TenantID, list[StreamID]] = {}
        self._filter_cache: dict[tuple, list[StreamID]] = {}
        self._file_path = os.path.join(path, STREAMS_FILENAME)
        if os.path.exists(self._file_path):
            self._load()
        self._file = open(self._file_path, "a", buffering=1 << 16)

    def _load(self) -> None:
        with open(self._file_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write after crash: ignore
                sid = StreamID(TenantID(rec["a"], rec["p"]),
                               rec["h"], rec["l"])
                self._register_mem(sid, rec["t"])

    def _register_mem(self, sid: StreamID, tags_str: str) -> None:
        if sid in self._streams:
            return
        self._streams[sid] = tags_str
        self._by_tenant.setdefault(sid.tenant, []).append(sid)

    def close(self) -> None:
        with self._lock:
            self._file.flush()
            self._file.close()

    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())

    # ---- write path ----
    def has_stream_id(self, sid: StreamID) -> bool:
        with self._lock:
            return sid in self._streams

    def must_register_stream(self, sid: StreamID, tags_str: str) -> None:
        self.must_register_streams([(sid, tags_str)])

    def must_register_streams(
            self, streams: list[tuple[StreamID, str]]) -> None:
        """Durably register new streams (fsynced before returning, so rows
        that reach a durable part can never reference an unindexed stream —
        the register-before-rows invariant partition.py relies on)."""
        with self._lock:
            wrote = False
            for sid, tags_str in streams:
                if sid in self._streams:
                    continue
                self._register_mem(sid, tags_str)
                self._file.write(json.dumps({
                    "a": sid.tenant.account_id, "p": sid.tenant.project_id,
                    "h": sid.hi, "l": sid.lo, "t": tags_str,
                }, separators=(",", ":")) + "\n")
                wrote = True
            if wrote:
                self._file.flush()
                os.fsync(self._file.fileno())
                # registrations invalidate cached filter results
                self._filter_cache.clear()

    # ---- read path ----
    def get_stream_tags(self, sid: StreamID) -> str | None:
        with self._lock:
            return self._streams.get(sid)

    def search_stream_ids(self, tenants: list[TenantID],
                          sf: StreamFilter) -> list[StreamID]:
        key = (tuple(tenants), sf)
        with self._lock:
            cached = self._filter_cache.get(key)
            if cached is not None:
                return cached
            out: list[StreamID] = []
            for t in tenants:
                for sid in self._by_tenant.get(t, ()):  # insertion order
                    tags = parse_stream_tags(self._streams[sid])
                    if sf.matches(tags):
                        out.append(sid)
            out.sort()
            if len(self._filter_cache) > 512:
                self._filter_cache.clear()
            self._filter_cache[key] = out
            return out

    def all_stream_ids(self, tenants: list[TenantID]) -> list[StreamID]:
        with self._lock:
            out: list[StreamID] = []
            for t in tenants:
                out.extend(self._by_tenant.get(t, ()))
            out.sort()
            return out

    def num_streams(self) -> int:
        with self._lock:
            return len(self._streams)
