"""On-disk part format: writer + lazy reader.

A part is an immutable directory of column-oriented files (the reference uses
13 file kinds — lib/logstorage/filenames.go:3-24, part.go:15-50; we collapse
to five with the same capabilities):

  metadata.json    part-level stats (rows, blocks, time range, sizes, version)
  index.bin        TWO-LEVEL block-header index (format v2): a small zstd
                   metaindex (per-group offset/length + block count + time
                   range) followed by independently-compressed GROUPS of
                   block headers (HEADER_GROUP_SIZE blocks each).  Opening
                   a part parses only the metaindex — O(groups) — and
                   header groups decode lazily on first touch, so
                   open+first-block cost stays flat as block counts grow
                   (reference index_block_header.go:1-175, where
                   metaindex.bin points at indexBlockHeader groups).
  timestamps.bin   per-block zstd(delta-encoded int64 nanos)
  columns.bin      per-(block,column) zstd-compressed payload regions
  blooms.bin       raw uint64 bloom words, memory-mapped at query time

Bloom words stay uncompressed on purpose: they are probed for *every* block a
query touches (the cheap kill-path), so they must be random-accessible without
a decompress step — the reader memory-maps them.

Format v1 (one zstd-JSON array of every header) remains readable: merges
naturally rewrite old parts into v2.
"""

from __future__ import annotations

import itertools
import json
import os
from dataclasses import dataclass

import numpy as np

from ..utils import zstd as _zstd
from .block import BlockData
from .log_rows import StreamID, TenantID
from .values_encoder import (EncodedColumn, VT_DICT, VT_FLOAT64, VT_INT64,
                             VT_IPV4, VT_STRING, VT_TIMESTAMP_ISO8601,
                             VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64)

FORMAT_VERSION = 2
HEADER_GROUP_SIZE = 256   # blocks per header group (v2 index.bin)

# Process-unique part identity for caches keyed across part lifetimes:
# id(part) is unsafe (CPython reuses freed addresses — ADVICE r1), so every
# Part/InmemoryPart draws a monotonic uid instead.
_part_uid_counter = itertools.count(1)


def next_part_uid() -> int:
    return next(_part_uid_counter)
METADATA_FILENAME = "metadata.json"
INDEX_FILENAME = "index.bin"
TIMESTAMPS_FILENAME = "timestamps.bin"
COLUMNS_FILENAME = "columns.bin"
BLOOMS_FILENAME = "blooms.bin"

_NUM_DTYPES = {
    VT_UINT8: np.uint8, VT_UINT16: np.uint16, VT_UINT32: np.uint32,
    VT_UINT64: np.uint64, VT_INT64: np.int64, VT_FLOAT64: np.float64,
    VT_IPV4: np.uint32, VT_TIMESTAMP_ISO8601: np.int64,
}

def _compress(data: bytes, hi: bool = False) -> bytes:
    return _zstd.compress(data, level=3 if hi else 1)


def _seal_column(c, hi: bool) -> bytes:
    """One column's compressed payload (pool-runnable: the payload
    gather + zstd both release the GIL; the compressed bytes are a pure
    function of the column, so pooled and serial parts are identical)."""
    return _compress(_column_payload(c), hi=hi)


def _decompress(data: bytes) -> bytes:
    return _zstd.decompress(data)


def write_part(path: str, blocks, big: bool = False,
               pool=None) -> dict | None:
    """Write blocks (already sorted by (stream_id, ts)) as a part directory.

    blocks may be any iterable of BlockData (e.g. the streaming merger) —
    it is consumed exactly once.  This is the SEAL point: the part never
    changes again, so the v2 filter index (split-block planes, xor
    aggregates, token→block maplets — storage/filterindex) is built here
    and written as a sidecar into the same directory, published by the
    same atomic rename.  Returns the filter-index build stats (or None
    when the build is pinned off / declined).

    pool: optional executor (the owning DataDB's block-build pool) —
    each block's timestamp + column payloads compress concurrently
    (zstd drops the GIL) and the sidecar builds per column on the same
    pool; results are written in deterministic order, so the part
    bytes never depend on the pool."""
    from . import filterindex as _fidx
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    headers = []
    total_rows = 0
    min_ts, max_ts = None, None
    comp_size = 0
    uncomp_size = 0
    fi_builder = _fidx.SidecarBuilder() if _fidx.enabled() else None
    fi_hash_s = 0.0   # pass-through re-tokenize cost (merges only)
    with open(os.path.join(tmp, TIMESTAMPS_FILENAME), "wb") as ts_f, \
         open(os.path.join(tmp, COLUMNS_FILENAME), "wb") as col_f, \
         open(os.path.join(tmp, BLOOMS_FILENAME), "wb") as bloom_f:
        ts_off = col_off = bloom_off = 0
        for b in blocks:
            total_rows += b.num_rows
            if min_ts is None or b.min_ts < min_ts:
                min_ts = b.min_ts
            if max_ts is None or b.max_ts > max_ts:
                max_ts = b.max_ts
            uncomp_size += b.uncompressed_size()
            # timestamps: delta-encode then zstd
            ts = b.timestamps
            deltas = np.empty_like(ts)
            deltas[0] = ts[0] if len(ts) else 0
            np.subtract(ts[1:], ts[:-1], out=deltas[1:])
            if pool is not None:
                ts_fut = pool.submit(_compress, deltas.tobytes(), big)
                col_futs = [pool.submit(_seal_column, c, big)
                            for c in b.columns]
                ts_z = ts_fut.result()
                sealed = [f.result() for f in col_futs]
            else:
                ts_z = _compress(deltas.tobytes(), hi=big)
                sealed = None
            ts_f.write(ts_z)
            ts_region = [ts_off, len(ts_z)]
            ts_off += len(ts_z)

            cols_hdr = []
            for ci, c in enumerate(b.columns):
                cz = sealed[ci] if sealed is not None \
                    else _seal_column(c, big)
                col_f.write(cz)
                ch = {"n": c.name, "t": c.vtype, "r": [col_off, len(cz)]}
                col_off += len(cz)
                if c.bloom is not None:
                    bloom_f.write(c.bloom.tobytes())
                    ch["b"] = [bloom_off, int(c.bloom.shape[0])]
                    bloom_off += c.bloom.shape[0] * 8
                    if fi_builder is not None:
                        # fresh blocks carry their hashes from the
                        # bloom build; merge pass-through blocks read
                        # back from disk recompute them (deterministic
                        # tokenizer over round-trip-exact values) —
                        # timed, so the new merge CPU cost stays
                        # visible in the build histogram and event
                        h = c.token_hashes
                        if h is None:
                            import time as _time
                            t_h = _time.perf_counter()
                            from .block import column_token_hashes
                            h = column_token_hashes(c, b.num_rows)
                            fi_hash_s += _time.perf_counter() - t_h
                        if h is not None:
                            fi_builder.add(len(headers), c.name, h)
                if c.vtype == VT_DICT:
                    ch["dict"] = c.dict_values
                elif c.vtype != VT_STRING:
                    ch["min"] = c.min_val
                    ch["max"] = c.max_val
                    if c.vtype == VT_TIMESTAMP_ISO8601:
                        ch["fw"] = c.iso_frac_w
                cols_hdr.append(ch)

            sid = b.stream_id
            headers.append({
                "sid": [sid.tenant.account_id, sid.tenant.project_id,
                        sid.hi, sid.lo],
                "tags": b.stream_tags_str,
                "rows": b.num_rows,
                "min_ts": b.min_ts, "max_ts": b.max_ts,
                "ts": ts_region,
                "cols": cols_hdr,
                "consts": b.const_columns,
            })
        comp_size = ts_off + col_off + bloom_off

        for fh in (ts_f, col_f, bloom_f):
            fh.flush()
            os.fsync(fh.fileno())
    fi_stats = None
    if fi_builder is not None and headers:
        import time as _time
        t0 = _time.perf_counter()
        try:
            fi_cols, fi_stats = _fidx.build_sidecar(fi_builder,
                                                    len(headers),
                                                    pool=pool)
            fi_stats["file_bytes"] = _fidx.write_sidecar(
                tmp, fi_cols, len(headers))
        # a part without a sidecar is correct, just slower — but a
        # deterministic build bug must stay visible in the journal
        # vlint: allow-broad-except(filter-index build is advisory)
        except Exception as e:
            from ..obs import events as _events
            _events.emit("filter_index_build_failed", part=path,
                         reason=repr(e))
            fi_stats = None
        else:
            from ..obs import hist as _hist
            fi_stats["build_s"] = round(_time.perf_counter() - t0, 6)
            fi_stats["hash_recompute_s"] = round(fi_hash_s, 6)
            # the histogram carries the WHOLE seal cost, re-tokenize
            # included — merge throughput regressions must show here
            _hist.FILTER_INDEX_BUILD.observe(fi_stats["build_s"]
                                             + fi_hash_s)
    # two-level index: compressed header GROUPS + a tiny metaindex that
    # locates them (open parses only the metaindex)
    groups_meta = []
    group_blobs = []
    goff = 0
    for g0 in range(0, len(headers), HEADER_GROUP_SIZE):
        grp = headers[g0:g0 + HEADER_GROUP_SIZE]
        blob = _compress(json.dumps(grp, separators=(",", ":"))
                         .encode("utf-8"), hi=True)
        groups_meta.append({
            "o": goff, "l": len(blob), "n": len(grp),
            "min_ts": min(h["min_ts"] for h in grp),
            "max_ts": max(h["max_ts"] for h in grp),
        })
        group_blobs.append(blob)
        goff += len(blob)
    metaindex_z = _compress(json.dumps(groups_meta, separators=(",", ":"))
                            .encode("utf-8"), hi=True)
    import struct as _struct
    with open(os.path.join(tmp, INDEX_FILENAME), "wb") as f:
        f.write(_struct.pack(">I", len(metaindex_z)))
        f.write(metaindex_z)
        for blob in group_blobs:
            f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    index_z_len = 4 + len(metaindex_z) + goff
    meta = {
        "format_version": FORMAT_VERSION,
        "rows": total_rows,
        "blocks": len(headers),
        "min_ts": min_ts or 0,
        "max_ts": max_ts or 0,
        "compressed_size": comp_size + index_z_len,
        "uncompressed_size": uncomp_size,
    }
    with open(os.path.join(tmp, METADATA_FILENAME), "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    # atomic publish: parts are immutable and always written to fresh names,
    # so a bare rename is the commit point (crash before it leaves only .tmp
    # garbage, which datadb removes at open — reference datadb.go:158-159).
    # All part files are fsynced above so the later parts.json fsync can never
    # durably reference a part whose data didn't hit the disk.
    if os.path.exists(path):
        import shutil
        shutil.rmtree(path)
    os.rename(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return fi_stats


def _column_payload(c: EncodedColumn) -> bytes:
    if c.vtype == VT_STRING:
        return (c.lengths.astype(np.int32).tobytes() + c.arena.tobytes())
    if c.vtype == VT_DICT:
        return c.ids.tobytes()
    return c.nums.tobytes()


@dataclass
class BlockHeader:
    """Parsed header of one block inside a part."""

    stream_id: StreamID
    stream_tags_str: str
    rows: int
    min_ts: int
    max_ts: int
    ts_region: tuple[int, int]
    cols: list[dict]
    consts: list[tuple[str, str]]

    def col_header(self, name: str) -> dict | None:
        for ch in self.cols:
            if ch["n"] == name:
                return ch
        return None


def _parse_header(h: dict) -> BlockHeader:
    a, p, hi, lo = h["sid"]
    return BlockHeader(
        stream_id=StreamID(TenantID(a, p), hi, lo),
        stream_tags_str=h.get("tags", ""),
        rows=h["rows"], min_ts=h["min_ts"], max_ts=h["max_ts"],
        ts_region=tuple(h["ts"]), cols=h["cols"],
        consts=[tuple(x) for x in h["consts"]],
    )


class LazyHeaders:
    """Sequence view over v2 header groups: each group decodes on first
    touch and is cached; untouched groups never pay decompress+parse."""

    def __init__(self, index_fd: int, base_off: int, groups_meta: list):
        import threading
        self._fd = index_fd
        self._base = base_off
        self._meta = groups_meta
        self._starts = []          # first block idx of each group
        pos = 0
        for g in groups_meta:
            self._starts.append(pos)
            pos += g["n"]
        self._n = pos
        self._groups: list[list[BlockHeader] | None] = \
            [None] * len(groups_meta)
        self._mu = threading.Lock()
        self.groups_loaded = 0     # test/observability hook

    def __len__(self) -> int:
        return self._n

    def _group_of(self, i: int) -> int:
        import bisect
        return bisect.bisect_right(self._starts, i) - 1

    def _load_group(self, gi: int) -> list:
        got = self._groups[gi]
        if got is not None:
            return got
        with self._mu:
            got = self._groups[gi]
            if got is not None:
                return got
            m = self._meta[gi]
            raw = _decompress(os.pread(self._fd, m["l"],
                                       self._base + m["o"]))
            got = [_parse_header(h) for h in json.loads(raw)]
            self._groups[gi] = got
            self.groups_loaded += 1
            return got

    def __getitem__(self, i: int) -> BlockHeader:
        if i < 0 or i >= self._n:
            raise IndexError(i)
        gi = self._group_of(i)
        return self._load_group(gi)[i - self._starts[gi]]

    def group_time_ranges(self):
        """(first_block, n_blocks, min_ts, max_ts) per group — candidate
        selection skips whole groups without decoding them."""
        for gi, m in enumerate(self._meta):
            yield self._starts[gi], m["n"], m["min_ts"], m["max_ts"]


class Part:
    """Lazy reader over an immutable part directory (or in-memory buffers)."""

    def __init__(self, path: str):
        import struct as _struct
        self.path = path
        self.uid = next_part_uid()
        with open(os.path.join(path, METADATA_FILENAME)) as f:
            self.meta = json.load(f)
        self._idx_f = open(os.path.join(path, INDEX_FILENAME), "rb")
        if self.meta.get("format_version", 1) >= 2:
            hlen = _struct.unpack(">I", self._idx_f.read(4))[0]
            groups_meta = json.loads(_decompress(self._idx_f.read(hlen)))
            self.headers = LazyHeaders(self._idx_f.fileno(), 4 + hlen,
                                       groups_meta)
        else:
            # format v1: one zstd-JSON array of every header (eager)
            self._idx_f.seek(0)
            raw = _decompress(self._idx_f.read())
            self.headers = [_parse_header(h) for h in json.loads(raw)]
        self._ts_f = open(os.path.join(path, TIMESTAMPS_FILENAME), "rb")
        self._col_f = open(os.path.join(path, COLUMNS_FILENAME), "rb")
        bloom_path = os.path.join(path, BLOOMS_FILENAME)
        if os.path.getsize(bloom_path) > 0:
            self._blooms = np.memmap(bloom_path, dtype=np.uint64, mode="r")
        else:
            self._blooms = np.zeros(0, dtype=np.uint64)

    # ---- properties ----
    @property
    def num_rows(self) -> int:
        return self.meta["rows"]

    @property
    def num_blocks(self) -> int:
        return len(self.headers)

    @property
    def min_ts(self) -> int:
        return self.meta["min_ts"]

    @property
    def max_ts(self) -> int:
        return self.meta["max_ts"]

    def close(self) -> None:
        self._ts_f.close()
        self._col_f.close()
        self._idx_f.close()

    def candidate_blocks(self, min_ts: int, max_ts: int):
        """Block idxs whose time range overlaps [min_ts, max_ts]; whole
        header groups outside the range are skipped WITHOUT decoding
        (v2 metaindex time ranges)."""
        if isinstance(self.headers, LazyHeaders):
            for gi, (start, n, g_min, g_max) in enumerate(
                    self.headers.group_time_ranges()):
                if g_min > max_ts or g_max < min_ts:
                    continue
                grp = self.headers._load_group(gi)
                for off, h in enumerate(grp):
                    if h.min_ts <= max_ts and h.max_ts >= min_ts:
                        yield start + off
            return
        for bi, h in enumerate(self.headers):
            if h.min_ts <= max_ts and h.max_ts >= min_ts:
                yield bi

    # ---- lazy block access ----
    # reads use os.pread: Part objects are shared between query threads,
    # the worker pool and background mergers, and a shared seek+read pair
    # races (observed as sporadic zstd errors under concurrent
    # flush+query load)
    def read_timestamps(self, block_idx: int) -> np.ndarray:
        h = self.headers[block_idx]
        off, ln = h.ts_region
        raw = os.pread(self._ts_f.fileno(), ln, off)
        deltas = np.frombuffer(_decompress(raw), dtype=np.int64)
        return np.cumsum(deltas)

    def read_bloom(self, ch: dict) -> np.ndarray | None:
        b = ch.get("b")
        if b is None:
            return None
        off_bytes, nwords = b
        start = off_bytes // 8
        return np.asarray(self._blooms[start:start + nwords])

    def read_column(self, block_idx: int, ch: dict) -> EncodedColumn:
        h = self.headers[block_idx]
        off, ln = ch["r"]
        payload = _decompress(os.pread(self._col_f.fileno(), ln, off))
        vt = ch["t"]
        col = EncodedColumn(name=ch["n"], vtype=vt)
        nrows = h.rows
        if vt == VT_STRING:
            lens = np.frombuffer(payload[:4 * nrows], dtype=np.int32) \
                     .astype(np.int64)
            col.lengths = lens
            col.offsets = np.zeros(nrows, dtype=np.int64)
            np.cumsum(lens[:-1], out=col.offsets[1:])
            col.arena = np.frombuffer(payload[4 * nrows:], dtype=np.uint8)
        elif vt == VT_DICT:
            col.ids = np.frombuffer(payload, dtype=np.uint8)
            col.dict_values = ch["dict"]
        else:
            col.nums = np.frombuffer(payload, dtype=_NUM_DTYPES[vt])
            col.min_val = ch.get("min", 0.0)
            col.max_val = ch.get("max", 0.0)
            col.iso_frac_w = ch.get("fw", 0)
        return col

    def read_block(self, block_idx: int) -> BlockData:
        h = self.headers[block_idx]
        cols = [self.read_column(block_idx, ch) for ch in h.cols]
        for c, ch in zip(cols, h.cols):
            c.bloom = self.read_bloom(ch)
        return BlockData(
            stream_id=h.stream_id,
            timestamps=self.read_timestamps(block_idx),
            columns=cols,
            const_columns=list(h.consts),
            stream_tags_str=h.stream_tags_str,
        )

    def iter_blocks(self):
        for i in range(self.num_blocks):
            yield self.read_block(i)

    # ---- uniform block-access API (shared with datadb.InmemoryPart) ----
    # The search executor schedules blocks through these accessors so that
    # in-memory and file parts look identical to it (the reference gets the
    # same effect from inmemoryPart mirroring the part file streams —
    # inmemory_part.go:13-27).

    def block_stream_id(self, i: int) -> StreamID:
        return self.headers[i].stream_id

    def block_tags(self, i: int) -> str:
        return self.headers[i].stream_tags_str

    def block_rows(self, i: int) -> int:
        return self.headers[i].rows

    def block_min_ts(self, i: int) -> int:
        return self.headers[i].min_ts

    def block_max_ts(self, i: int) -> int:
        return self.headers[i].max_ts

    def block_consts(self, i: int) -> list[tuple[str, str]]:
        return self.headers[i].consts

    def block_col_names(self, i: int) -> list[str]:
        return [ch["n"] for ch in self.headers[i].cols]

    def block_column_meta(self, i: int, name: str) -> dict | None:
        """Column metadata without reading the payload (vtype, min/max, dict)."""
        return self.headers[i].col_header(name)

    def block_column_bloom(self, i: int, name: str) -> np.ndarray | None:
        ch = self.headers[i].col_header(name)
        if ch is None:
            return None
        return self.read_bloom(ch)

    def block_column(self, i: int, name: str) -> EncodedColumn | None:
        ch = self.headers[i].col_header(name)
        if ch is None:
            return None
        col = self.read_column(i, ch)
        col.bloom = self.read_bloom(ch)
        return col

    def block_timestamps(self, i: int) -> np.ndarray:
        return self.read_timestamps(i)
