"""victorialogs_tpu — a TPU-native log database with the capabilities of VictoriaLogs.

Not a port: the storage/server core runs on the host, while the hot query
path (bloom probes, token/phrase/substring/regex matching, bitmap reductions,
stats aggregations) executes as JAX/XLA/Pallas programs on TPU, with
multi-chip aggregation over ICI (`psum`) and cluster fan-out over DCN.

Layer map (mirrors reference layers in /root/repo/SURVEY.md §1):
  storage/   — columnar LSM engine (parts, blocks, blooms, stream index)
  logsql/    — LogsQL lexer/parser, filter tree, pipes, stats functions
  engine/    — search executor: block scheduling, block scan, result batches
  tpu/       — device plane: block staging + JAX/Pallas kernels
  parallel/  — mesh/psum distribution, cluster scatter-gather
  server/    — HTTP apps: vlinsert / vlselect / vlstorage / single binary
  cli/       — vlogscli REPL, vlogsgenerator load generator
  native/    — C++ runtime module (zstd, xxhash, tokenizer) via ctypes
"""

__version__ = "0.1.0"
