"""Central registry of VL_* environment knobs and vl_* metric names.

Every ``VL_*`` environment variable the process reads and every
``vl_*`` metric name it rolls is DECLARED here, once, with its default,
type and documentation.  Two consumers depend on that single source of
truth:

- the vlint ``env-registry`` / ``metric-registry`` checkers
  (tools/vlint/registry.py) flag raw ``os.environ`` reads and
  undeclared / double-rolled metric names anywhere else in the tree,
  so a new knob or counter cannot ship without its declaration;
- ``render_env_table()`` generates the README environment-variable
  table, and ``make lint`` fails when the committed README drifts from
  the registry — documentation rot became a lint failure, not a
  review catch.

This module must stay import-light (stdlib ``os`` only): the linter
loads it standalone via importlib, outside the package, and the
earliest package imports (native/, utils/) read it at import time.

Reading knobs
-------------
All readers re-read ``os.environ`` on every call (kill-switches are
flipped per-test via monkeypatch); nothing here caches values:

- ``env(name[, default])``      -> raw string (declared default when unset)
- ``env_int(name[, default])``  -> int; unset/empty/invalid -> default
- ``env_float(name[, default])``-> float; same fallback rule
- ``env_flag(name)``            -> bool, the `!= "0"` idiom (on unless "0")
- ``env_bool(name)``            -> bool, explicit truthy set (1/true/yes/on)

Reading an undeclared name raises ``UndeclaredEnvVar`` — the runtime
twin of the static checker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_U = object()          # "no per-call default supplied" sentinel


class UndeclaredEnvVar(KeyError):
    """An env read bypassed the declarations below — declare it first."""


class UndeclaredMetric(KeyError):
    """A metric name was used without a declaration below."""


# ---------------------------------------------------------------- env vars

@dataclass(frozen=True)
class EnvVar:
    name: str
    default: str | None     # parsing default; None = unset/off
    kind: str               # "flag" | "bool" | "int" | "float" | "str"
    doc: str                # one line, README table cell
    display: str            # README "default" cell

    def table_row(self) -> str:
        return f"| `{self.name}` | {self.display} | {self.doc} |"


_ENV: dict[str, EnvVar] = {}

_ENV_KINDS = ("flag", "bool", "int", "float", "str")


def declare_env(name: str, default: str | None, kind: str, doc: str,
                display: str | None = None) -> None:
    if name in _ENV:
        raise ValueError(f"duplicate env declaration: {name}")
    if kind not in _ENV_KINDS:
        raise ValueError(f"bad env kind {kind!r} for {name}")
    if not doc:
        raise ValueError(f"env declaration {name} needs a doc string")
    if display is None:
        display = "unset" if default is None else f"`{default}`"
    _ENV[name] = EnvVar(name, default, kind, doc, display)


def env_vars() -> dict[str, EnvVar]:
    return dict(_ENV)


def _decl(name: str) -> EnvVar:
    try:
        return _ENV[name]
    except KeyError:
        raise UndeclaredEnvVar(
            f"{name} is not declared in victorialogs_tpu/config.py — "
            f"declare_env() it (name, default, kind, doc) before reading"
        ) from None


def env(name: str, default=_U) -> str | None:
    """Raw string value (the declared default when unset)."""
    d = _decl(name)
    v = os.environ.get(name)
    if v is None:
        return d.default if default is _U else default
    return v


def env_int(name: str, default=_U) -> int | None:
    """int value; unset, empty or unparseable falls back to the default
    (the declared one unless a call-site default is given — dynamic
    defaults like VL_QUEUE_MAX's 2x max live at the call site)."""
    d = _decl(name)
    fb = d.default if default is _U else default
    v = os.environ.get(name)
    if v is not None and v != "":
        try:
            return int(v)
        except ValueError:
            pass
    if fb is None:
        return None
    return int(fb)


def env_float(name: str, default=_U) -> float | None:
    d = _decl(name)
    fb = d.default if default is _U else default
    v = os.environ.get(name)
    if v is not None and v != "":
        try:
            return float(v)
        except ValueError:
            pass
    if fb is None:
        return None
    return float(fb)


def env_flag(name: str) -> bool:
    """The kill-switch idiom: on unless the value is exactly "0"."""
    d = _decl(name)
    return os.environ.get(name, d.default or "") != "0"


_TRUTHY = ("1", "true", "yes", "on")


def env_bool(name: str) -> bool:
    """Explicit opt-in idiom: true only for 1/true/yes/on."""
    d = _decl(name)
    return (os.environ.get(name) or d.default or "").lower() in _TRUTHY


# Declarations, in README-table order (device pipeline -> emit -> wire
# -> filters -> observability -> scheduling -> fault tolerance -> misc).

declare_env(
    "VL_INFLIGHT", "4", "str",
    "async device pipeline window: how many parts/packs keep dispatches "
    "outstanding; `1` = serial submit-then-harvest walk; `auto` = derive "
    "depth from the measured dispatch RTT and the per-unit emit EWMA "
    "(ceil(rtt/emit), clamped to [2, 16]; chosen depth exported as "
    "`vl_tpu_inflight_auto_depth`) (`tpu/pipeline.py`)")
declare_env(
    "VL_NATIVE_EMIT", "1", "flag",
    "`0` = kill-switch for the columnar NDJSON serializer: query "
    "responses fall back to the per-row dict + `json.dumps` path "
    "(bit-identical bytes — `engine/emit.py`, `tests/test_emit.py`)")
declare_env(
    "VL_WIRE_TYPED", "1", "flag",
    "`0` = kill-switch for the typed columnar cluster wire format: this "
    "process neither requests nor serves typed frames, so every "
    "internal-select hop uses the legacy list-of-strings JSON frames "
    "(bit-identical results — `server/cluster.py`, `tests/test_wire.py`)")
declare_env(
    "VL_WIRE_TYPED_INSERT", "1", "flag",
    "`0` = kill-switch for the typed ingest wire format \"i1\": this "
    "process neither encodes nor accepts typed insert frames — "
    "frontends/vlagent ship legacy zstd'd JSON lines and storage nodes "
    "reject i1 bodies with a 400 so senders pin them to legacy "
    "(`server/wire_ingest.py`, `tests/test_wire_ingest.py`)")
declare_env(
    "VL_PACK_PARTS", "8", "int",
    "max small parts folded into one fused super-dispatch; `1` = "
    "packing off (kill-switch)")
declare_env(
    "VL_PACK_TOPK_K", "1024", "int",
    "largest `sort ... limit` k eligible for packed sort-topk "
    "super-dispatches (the packed dispatch k-selects once per member, "
    "so cost grows with pack_size * k); `0` = sort-topk packing off "
    "(`tpu/pipeline.py`)")
declare_env(
    "VL_CROSS_PARTITION", "1", "flag",
    "`0` = kill-switch for the cross-partition dispatch window: the "
    "device pipeline drains at every day-partition boundary like "
    "pre-PR-15 (per-partition prefetch depth, no boundary-spanning "
    "packs — `engine/searcher.py`, `tpu/pipeline.py`)")
declare_env(
    "VL_PACK_MAX_ROWS", None, "int",
    "parts above this many rows never pack; default scales with the "
    "measured dispatch RTT (floor 16k rows, cap 1M — flush-sized parts "
    "always pack, big parts only when the RTT dwarfs their scan)",
    display="adaptive")
declare_env(
    "VL_FUSED_FILTER", "1", "flag",
    "`0` = row queries use the round-3 per-leaf dispatch path instead "
    "of the single fused filter program")
declare_env(
    "VL_DEVICE_BLOOM", "1", "flag",
    "`0` = bloom keep-masks stay host-side instead of probing "
    "in-dispatch")
declare_env(
    "VL_PALLAS", None, "str",
    "`1` = Pallas kernels (gated until profiled on hardware)",
    display="off")
declare_env(
    "VL_COST_FORCE", None, "str",
    "`device`/`host` pins the per-part cost-gate decision",
    display="unset")
declare_env(
    "VL_COST_RTT_MS", None, "float",
    "preseed the cost-model dispatch-RTT calibration (milliseconds)",
    display="measured")
declare_env(
    "VL_COST_DEV_GBPS", None, "float",
    "preseed the cost-model device-throughput calibration (GB/s)",
    display="measured")
declare_env(
    "VL_COST_HOST_MROWS", None, "float",
    "preseed the cost-model host-scan calibration (Mrows/s)",
    display="measured")
declare_env(
    "VL_BLOOM_PLANE_MAX_BYTES", str(256 << 20), "int",
    "per-plane host bloom-plane size cap (`storage/filterbank.py`); "
    "larger planes decline to the per-block path",
    display="256 MiB")
declare_env(
    "VL_BLOOM_BANK_MAX_BYTES", str(1 << 30), "int",
    "global budget for ALL host-resident bloom planes "
    "(`storage/filterbank.py`); loaded v2 filter-index sidecars charge "
    "the same bank, released by weakref finalize at part GC",
    display="1 GiB")
declare_env(
    "VL_FILTER_INDEX", None, "str",
    "`v1` = pin the classic blooms.bin path: sealed parts neither build "
    "nor read `filterindex.bin` sidecars (split-block planes / xor "
    "aggregates / maplets off — `storage/filterindex/`, bit-identical "
    "results)",
    display="`v2`")
declare_env(
    "VL_FILTER_INDEX_REBUILD", "0", "flag",
    "`1` = rebuild missing `filterindex.bin` sidecars for pre-v2 "
    "sealed parts IN PLACE at part-open time (from blooms.bin + "
    "columns, the same deterministic tokenizer as the seal-time "
    "build), so long-lived deployments get maplet/xor/split-block "
    "pruning without waiting for a merge; journaled as "
    "`filter_index_built` with `rebuilt=true` "
    "(`storage/filterindex/index.py`)")
declare_env(
    "VL_QUERY_PRICING", "1", "flag",
    "`0` = kill the continuous plan-time pricing pass: queries no "
    "longer compute `predicted_*` costs, `query_done` events lose the "
    "predicted-vs-actual pair and the `vl_cost_model_rel_error_*` "
    "histograms stop feeding (`obs/explain.py`; the `?explain=` "
    "endpoints stay available)")
declare_env(
    "VL_SLOW_QUERY_MS", None, "int",
    "slow-query log threshold: queries over it emit one structured "
    "JSON line (stderr) with the flattened per-stage trace summary "
    "(`victorialogs_tpu/obs/slowlog.py`)",
    display="off")
declare_env(
    "VL_JOURNAL", "1", "flag",
    "`0` = kill the self-telemetry journal: no event-bus subscriber, "
    "`events.emit()` structurally free (`obs/events.py`, "
    "`obs/journal.py`)")
declare_env(
    "VL_JOURNAL_FLUSH_MS", "500", "int",
    "journal flush cadence: how often queued events batch into "
    "`LogRows` and ingest under the system tenant")
declare_env(
    "VL_JOURNAL_MAX_QUEUE", "4096", "int",
    "journal queue bound; events past it drop (counted exact in "
    "`vl_journal_dropped_total`) — a wedged flush never blocks a query")
declare_env(
    "VL_JOURNAL_FLUSH_DEADLINE_MS", "5000", "int",
    "journal flush wall-time alarm: flushes over it count in "
    "`vl_journal_flushes_slow_total`")
declare_env(
    "VL_SCHED", "1", "flag",
    "`0` = disable the shared dispatch scheduler (every query burns its "
    "own window unmanaged — the pre-scheduler behavior, used as the "
    "bench baseline)")
declare_env(
    "VL_INFLIGHT_GLOBAL", "8", "int",
    "shared device-dispatch budget: max dispatch slots outstanding "
    "process-wide across ALL queries; per-query windows lease from it "
    "with weighted fair queuing (`victorialogs_tpu/sched/scheduler.py`)")
declare_env(
    "VL_MAX_CONCURRENT", "8", "int",
    "admission control: max queries executing per pool (select / "
    "cluster-internal) when the server ctor doesn't pin it "
    "(`sched/admission.py`)")
declare_env(
    "VL_TENANT_MAX_CONCURRENT", "0", "int",
    "per-tenant concurrency cap; over-limit arrivals shed 429 "
    "`reason=tenant_limit` (runtime per-tenant override via "
    "`POST /select/logsql/sched_config`)",
    display="= max")
declare_env(
    "VL_TENANT_MAX_BYTES", "0", "int",
    "per-tenant estimated bytes-in-flight budget (per-endpoint "
    "bytes-scanned EWMA); over-budget arrivals shed "
    "`reason=tenant_limit`",
    display="off")
declare_env(
    "VL_QUEUE_MAX", None, "int",
    "admission wait-queue bound; past it arrivals shed 429 "
    "`reason=queue_full` instead of queuing unboundedly",
    display="2×max")
declare_env(
    "VL_QUEUE_TIMEOUT_MS", "30000", "int",
    "max admission-queue wait (the old `-search.maxQueueDuration`); "
    "expiry sheds 429")
declare_env(
    "VL_TENANT_WEIGHTS", None, "str",
    "fair-share weights for the dispatch scheduler, e.g. "
    "`0:0=4,9:0=0.5` (runtime override via `sched_config`)",
    display="unset")
declare_env(
    "VL_FAULT_SUBMIT", None, "float",
    "fault injection: fail each dispatch submit with this probability "
    "(test/chaos hook; `sched.inject_fault()` is the deterministic "
    "one-shot form)",
    display="off")
declare_env(
    "VL_FAULT_NET", None, "str",
    "network fault injection: `refuse:0.2` / `5xx:1.0` fails each "
    "cluster HTTP attempt with that probability "
    "(`sched.inject_net_fault()` is the deterministic one-shot form; "
    "wire-level hang/reset/trickle modes ride the in-process "
    "`sched.FaultProxy`)",
    display="off")
declare_env(
    "VL_PARTIAL_RESULTS", "0", "bool",
    "`1` = default queries to partial-results mode: when a storage node "
    "is still down after retries, scatter-gather answers from the "
    "survivors, marked `X-VL-Partial: true` + a `partial.failed_nodes` "
    "block (per-request `?partial=1/0` overrides; default stays the "
    "reference's strict fail-the-whole-query)")
declare_env(
    "VL_NET_RETRIES", "2", "int",
    "extra attempts per idempotent select sub-query after the first "
    "(jittered exponential backoff, never past the request deadline, "
    "never after a frame was delivered; `0` disables)")
declare_env(
    "VL_NET_HEDGE_MS", None, "str",
    "straggler hedging delay: after this long without a first frame "
    "the sub-query is re-issued to the same node and the first answer "
    "wins (`auto` = p95-style EWMA of first-frame RTTs once 8 samples "
    "exist; `0` = off)",
    display="auto")
declare_env(
    "VL_BREAKER_FAILURES", "2", "int",
    "consecutive transport/5xx failures that open a node's circuit "
    "(shared select+insert breaker, `server/netrobust.py`)")
declare_env(
    "VL_BREAKER_OPEN_S", "10", "float",
    "seconds an open circuit refuses requests before half-opening a "
    "single probe (ingest 429s instead park only the node's INSERT "
    "path for their `Retry-After`, uncounted — selects keep flowing)")
declare_env(
    "VL_INSERT_SPOOL_MAX_BYTES", str(256 << 20), "int",
    "per-node durable ingest spool bound on cluster frontends: batches "
    "that exhaust every healthy node spool to disk and replay on "
    "recovery; past the bound they drop loudly (counted + journaled; "
    "`0` disables spooling)",
    display="256 MiB")
declare_env(
    "VL_CLUSTER_STATS_MS", "1000", "int",
    "cluster frontends poll every storage node's `GET /internal/usage` "
    "on this cadence, rolling per-tenant usage up into "
    "`vl_cluster_tenant_*_total` and node liveness into "
    "`vl_cluster_node_up{node=}` on the frontend /metrics plus "
    "`GET /select/logsql/tenants` (`obs/clusterstats.py`; `0` disables "
    "the poll loop)")
declare_env(
    "VL_INGEST_TRACE", "0", "bool",
    "`1` = per-batch ingest span trees: every accepted batch grows a "
    "real `obs/tracing.py` tree (one child span per hop: parse/encode/"
    "shard/ship/spool/replay/decode/store) surfaced on "
    "`GET /insert/status` and in `ingest_batch` journal events; off, "
    "only the always-on per-(tenant, hop) latency aggregates roll "
    "(`obs/ingestledger.py`; bench-asserted <=1.10x when off)")
declare_env(
    "VL_INGEST_BATCHES_MAX", "512", "int",
    "max in-flight ingest batch records the row-conservation ledger "
    "tracks; past it the oldest records evict to the completed ring "
    "(counters are unaffected — only per-batch detail is bounded)")
declare_env(
    "VL_MEMORY_ALLOWED_BYTES", None, "int",
    "query memory budget", display="auto")
declare_env(
    "VL_INGEST_THREADS", "1", "int",
    "ingest shard parallelism: bodies over 8 MB split at newline "
    "boundaries across this many workers, each scanning/assembling its "
    "own columnar batch and handing it to the sink on the worker "
    "(`server/vlinsert.py`)", display="auto")
declare_env(
    "VL_BLOCK_BUILD_THREADS", None, "int",
    "block-build shard parallelism on the storage flush path: each "
    "size-bounded block chunk's values-encode + token blooms builds "
    "on a per-DataDB thread pool, and part seals compress columns / "
    "build filter-index sidecar columns on the same pool "
    "(`storage/block_build.py`; flushed parts are byte-identical to "
    "the serial build; `0`/`1` = serial; default min(cores, 8))",
    display="auto")
declare_env(
    "VL_ARENA_BUILD", "1", "flag",
    "`1` = columnar values-encode: ASCII i1 wire columns feed block "
    "build as offset slices over the decoded arena, with vectorized "
    "const/dict/int/float detection — no per-row Python strings "
    "between `decode_frame` and the encoded block; `0` = always "
    "materialize per-row strings first (same bytes either way)")
declare_env(
    "VL_INSERT_PIPELINE", "0", "int",
    "storage-node `/internal/insert` hop overlap: depth of the "
    "bounded decode->store hand-off queue, letting frame N+1 decode "
    "while frame N builds blocks (rows count as ledger in-flight "
    "until stored; `0` = synchronous store on the request thread)")
declare_env(
    "VL_NO_NATIVE", None, "str",
    "`1` = skip the C++ host core, numpy fallbacks", display="off")
declare_env(
    "VL_XLA_TRACE_DIR", None, "str",
    "XLA profiler traces at the runner seam", display="off")
declare_env(
    "VL_RESULT_CACHE", "1", "bool",
    "per-part result cache (`engine/standing/resultcache.py`): "
    "repeated queries replay sealed parts' cached stats partials / "
    "filter bitmaps and re-dispatch only the unsealed head; `0` "
    "disables (every part recomputes)")
declare_env(
    "VL_RESULT_CACHE_MAX_BYTES", str(64 << 20), "int",
    "byte budget for the per-part result cache; past it LRU entries "
    "evict (counted + journaled as `result_cache_evict`), and a "
    "part's GC releases its entries' bytes like the bloom bank",
    display="64 MiB")
declare_env(
    "VL_STANDING", "1", "bool",
    "standing-query registration (`POST /select/logsql/"
    "standing_query`): one resident evaluation per distinct query "
    "fingerprint, re-run on storage flush/merge and fanned out to all "
    "subscribers; `0` refuses registrations (503)")
declare_env(
    "VL_STANDING_MAX", "64", "int",
    "max standing-query entries per node; past it registrations are "
    "refused with 429")
declare_env(
    "VL_STANDING_DEBOUNCE_MS", "100", "int",
    "coalescing window for standing re-evaluation: flush/merge bursts "
    "inside it trigger ONE re-run per registered query")


_TABLE_HEADER = ("| Variable | Default | Meaning |",
                 "|---|---|---|")


def render_env_table() -> str:
    """The README environment-variable table, generated from the
    declarations above (one row per variable, declaration order).
    ``make lint`` fails when the committed README section differs."""
    rows = list(_TABLE_HEADER)
    rows.extend(v.table_row() for v in _ENV.values())
    return "\n".join(rows) + "\n"


# ---------------------------------------------------------------- metrics

@dataclass(frozen=True)
class Metric:
    name: str
    kind: str               # "counter" | "gauge" | "histogram"
    help: str
    single_roll: bool       # True: exactly ONE static roll site allowed


_METRICS: dict[str, Metric] = {}

_METRIC_KINDS = ("counter", "gauge", "histogram")

# name spaces minted dynamically (runner stats keys render as
# vl_tpu_<key>); the static metric-registry checker cannot resolve
# them, so the vlsan runtime sweep guards them (non-negative) instead
DYNAMIC_METRIC_PREFIXES = ("vl_tpu_",)


def declare_metric(name: str, kind: str, help: str,
                   single_roll: bool = False) -> None:
    if name in _METRICS:
        raise ValueError(f"duplicate metric declaration: {name}")
    if kind not in _METRIC_KINDS:
        raise ValueError(f"bad metric kind {kind!r} for {name}")
    if not help:
        raise ValueError(f"metric declaration {name} needs help text")
    # server/app.py Metrics.render infers counter-vs-gauge from the
    # _total suffix; a declaration disagreeing with the renderer would
    # lie on /metrics
    if kind == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter {name} must end in _total")
    if kind == "gauge" and name.endswith("_total"):
        raise ValueError(f"gauge {name} must not end in _total")
    _METRICS[name] = Metric(name, kind, help, single_roll)


def metric_decls() -> dict[str, Metric]:
    return dict(_METRICS)


def metric_declared(name: str) -> bool:
    if name in _METRICS:
        return True
    return any(name.startswith(p) for p in DYNAMIC_METRIC_PREFIXES)


# -- HTTP layer (server/app.py) --
declare_metric("vl_http_errors_total", "counter",
               "HTTP requests answered with a 5xx/unhandled error")
declare_metric("vl_http_requests_total", "counter",
               "HTTP requests by path", single_roll=True)
declare_metric("vl_http_request_duration_ms_total", "counter",
               "cumulative request wall time by path, milliseconds",
               single_roll=True)
declare_metric("vl_http_request_queue_timeouts_total", "counter",
               "requests shed after exceeding the admission queue wait",
               single_roll=True)
declare_metric("vl_queries_cancelled_total", "counter",
               "queries terminated via POST cancel_query",
               single_roll=True)
declare_metric("vl_rows_ingested_total", "counter",
               "rows accepted into storage by ingest protocol")
declare_metric("vl_ingest_bytes_total", "counter",
               "decompressed ingest payload bytes by protocol")
declare_metric("vl_ingest_parse_failures_total", "counter",
               "ingest payloads rejected as unparseable, by protocol")
declare_metric("vl_build_info", "gauge",
               "constant 1, labeled with version/app", single_roll=True)
declare_metric("vl_uptime_seconds", "gauge",
               "seconds since server start", single_roll=True)
declare_metric("vl_invalid_metric_name", "gauge",
               "defensive bucket for malformed stored sample names",
               single_roll=True)

# -- storage gauges (server/app.py render <- storage.update_stats) --
declare_metric("vl_partitions", "gauge", "live partitions")
declare_metric("vl_streams_created_total", "counter",
               "log streams ever registered")
declare_metric("vl_storage_rows", "gauge",
               "stored rows by part tier (inmemory/file/small/big)")
declare_metric("vl_storage_parts", "gauge",
               "live parts by tier")
declare_metric("vl_data_size_bytes", "gauge",
               "compressed on-disk size")
declare_metric("vl_uncompressed_data_size_bytes", "gauge",
               "uncompressed logical size")
declare_metric("vl_rows_dropped_total", "counter",
               "ingested rows dropped by retention (too_old/too_new)")
declare_metric("vl_storage_is_read_only", "gauge",
               "1 when the storage rejects writes (disk budget)")
declare_metric("vl_storage_pending_merges", "gauge",
               "queued tier compactions")
declare_metric("vl_storage_merges_total", "counter",
               "part merges completed")
declare_metric("vl_storage_flush_age_seconds", "gauge",
               "staleness of the oldest in-RAM rows")
declare_metric("vl_storage_merge_duration_seconds", "histogram",
               "wall time of one part merge")

# -- filter bank / device budget --
declare_metric("vl_tpu_bloom_bank_used_bytes", "gauge",
               "host bloom-plane budget occupancy", single_roll=True)
declare_metric("vl_tpu_bloom_bank_max_bytes", "gauge",
               "host bloom-plane budget bound", single_roll=True)
declare_metric("vl_filter_index_build_seconds", "histogram",
               "seal-time filterindex.bin sidecar build wall time")

# -- active-query registry / per-tenant accounting (obs/activity.py) --
declare_metric("vl_active_queries", "gauge",
               "live query executions (total + per endpoint)")
declare_metric("vl_tenant_select_queries_total", "counter",
               "completed select queries per tenant", single_roll=True)
declare_metric("vl_tenant_select_seconds_total", "counter",
               "select execution seconds per tenant", single_roll=True)
declare_metric("vl_tenant_bytes_scanned_total", "counter",
               "bytes scanned per tenant", single_roll=True)
declare_metric("vl_tenant_rows_ingested_total", "counter",
               "rows ingested per tenant", single_roll=True)
declare_metric("vl_tenant_ingest_bytes_total", "counter",
               "decompressed ingest bytes per tenant", single_roll=True)

# -- admission + dispatch scheduler (victorialogs_tpu/sched) --
declare_metric("vl_select_rejected_total", "counter",
               "admission sheds by pool/reason/tenant", single_roll=True)
declare_metric("vl_select_admitted_total", "counter",
               "admission grants by pool/tenant", single_roll=True)
declare_metric("vl_sched_queue_depth", "gauge",
               "admission queue depth per pool", single_roll=True)
declare_metric("vl_sched_admission_active", "gauge",
               "queries executing per admission pool", single_roll=True)
declare_metric("vl_sched_dispatch_budget", "gauge",
               "VL_INFLIGHT_GLOBAL shared dispatch budget",
               single_roll=True)
declare_metric("vl_sched_dispatch_in_flight", "gauge",
               "dispatch slots currently leased", single_roll=True)
declare_metric("vl_sched_dispatch_grants_total", "counter",
               "slot leases ever granted", single_roll=True)
declare_metric("vl_sched_dispatch_contended_total", "counter",
               "non-blocking lease attempts that found no free slot",
               single_roll=True)

# -- event bus + journal (obs/events.py, obs/journal.py) --
declare_metric("vl_journal_events_total", "counter",
               "events delivered to at least one subscriber",
               single_roll=True)
declare_metric("vl_journal_suppressed_total", "counter",
               "events suppressed by the recursion guard",
               single_roll=True)
declare_metric("vl_journal_subscriber_errors_total", "counter",
               "subscriber callbacks that raised", single_roll=True)
declare_metric("vl_trace_children_dropped_total", "counter",
               "span children dropped at MAX_CHILDREN")
declare_metric("vl_slowlog_emit_failures_total", "counter",
               "slow-query log lines whose sink write failed",
               single_roll=True)
declare_metric("vl_top_queries_evicted_total", "counter",
               "completed-query ring evictions", single_roll=True)
declare_metric("vl_journal_dropped_total", "counter",
               "journal events dropped at the bounded queue",
               single_roll=True)
declare_metric("vl_journal_rows_written_total", "counter",
               "journal rows ingested into storage", single_roll=True)
declare_metric("vl_journal_queue_depth", "gauge",
               "journal events waiting to flush", single_roll=True)
declare_metric("vl_journal_flushes_total", "counter",
               "journal flush batches written", single_roll=True)
declare_metric("vl_journal_flushes_slow_total", "counter",
               "journal flushes over the cadence deadline",
               single_roll=True)
declare_metric("vl_journal_flush_errors_total", "counter",
               "journal flush attempts that raised", single_roll=True)

# -- cluster wire protocol (server/cluster.py) --
declare_metric("vl_wire_frames_total", "counter",
               "internal-select frames by dir (tx/rx) and format "
               "(typed/json)", single_roll=True)
declare_metric("vl_wire_bytes_total", "counter",
               "internal-select payload bytes by dir and format",
               single_roll=True)
declare_metric("vl_wire_fallbacks_total", "counter",
               "typed-requesting frontends answered with JSON frames",
               single_roll=True)

# -- typed ingest wire (server/wire_ingest.py) --
declare_metric("vl_ingest_wire_frames_total", "counter",
               "insert wire bodies by dir (tx/rx) and format "
               "(typed/json)", single_roll=True)
declare_metric("vl_ingest_wire_bytes_total", "counter",
               "insert wire body bytes (compressed) by dir and format",
               single_roll=True)
declare_metric("vl_ingest_wire_fallbacks_total", "counter",
               "insert hops pinned from i1 back to legacy JSON lines",
               single_roll=True)

# -- cluster fault policy (server/netrobust.py) --
declare_metric("vl_node_health", "gauge",
               "per-node breaker state: 1 closed, 0.5 half-open, 0 open",
               single_roll=True)
declare_metric("vl_node_breaker_opens_total", "counter",
               "circuit-breaker open transitions", single_roll=True)
declare_metric("vl_net_retries_total", "counter",
               "cluster sub-query retry attempts", single_roll=True)
declare_metric("vl_net_hedges_total", "counter",
               "hedged sub-queries by outcome (won=)", single_roll=True)
declare_metric("vl_partial_results_total", "counter",
               "queries answered partial (X-VL-Partial)",
               single_roll=True)
declare_metric("vl_insert_spooled_blocks_total", "counter",
               "ingest batches spooled to disk during node outages",
               single_roll=True)
declare_metric("vl_insert_replayed_blocks_total", "counter",
               "spooled ingest batches replayed on recovery",
               single_roll=True)
declare_metric("vl_insert_spool_overflow_total", "counter",
               "ingest batches dropped at the spool byte bound",
               single_roll=True)
declare_metric("vl_insert_spool_bytes", "gauge",
               "bytes currently spooled per node")
declare_metric("vl_insert_spool_entries", "gauge",
               "blocks currently spooled per node")
declare_metric("vl_insert_spool_oldest_age_seconds", "gauge",
               "age of the oldest unreplayed spool block per node")

# -- ingest observability plane (obs/ingestledger.py) --
declare_metric("vl_ingest_ledger_rows_total", "counter",
               "row-conservation ledger counters by tenant and state "
               "(accepted/received/forwarded/spooled/replayed/stored)",
               single_roll=True)
declare_metric("vl_ingest_ledger_dropped_total", "counter",
               "rows terminally dropped by tenant and reason "
               "(the ledger's only loss exit)", single_roll=True)
declare_metric("vl_ingest_ledger_in_flight", "gauge",
               "derived in-flight rows per tenant: accepted+received "
               "- stored - forwarded - dropped", single_roll=True)
declare_metric("vl_ingest_batches_in_flight", "gauge",
               "ingest batches currently tracked by the ledger",
               single_roll=True)
declare_metric("vl_ingest_watermark_seconds", "gauge",
               "per-tenant freshness lag: seconds since the max stored "
               "row timestamp", single_roll=True)

# -- /internal/insert decode/build overlap (server/cluster.py) --
declare_metric("vl_insert_pipeline_batches_total", "counter",
               "typed insert batches handed to the decode/build overlap "
               "pipeline (VL_INSERT_PIPELINE > 0)", single_roll=True)
declare_metric("vl_insert_pipeline_rows_stored_total", "counter",
               "rows stored by the insert pipeline drainer",
               single_roll=True)
declare_metric("vl_insert_pipeline_rows_dropped_total", "counter",
               "rows dropped by the insert pipeline drainer on store "
               "failure (also rolled into the ledger as "
               "pipeline_store_error)", single_roll=True)
declare_metric("vl_insert_pipeline_queue_depth", "gauge",
               "batches currently queued behind the insert pipeline "
               "drainer", single_roll=True)

# -- cluster observability plane (obs/clusterstats.py, federated
#    registry + cancel propagation in server/cluster.py + app.py) --
declare_metric("vl_cluster_tenant_select_seconds_total", "counter",
               "select execution seconds per tenant summed across all "
               "storage nodes (frontend rollup)", single_roll=True)
declare_metric("vl_cluster_tenant_bytes_scanned_total", "counter",
               "bytes scanned per tenant summed across all storage "
               "nodes (frontend rollup)", single_roll=True)
declare_metric("vl_cluster_tenant_rows_ingested_total", "counter",
               "rows ingested per tenant summed across all storage "
               "nodes (frontend rollup)", single_roll=True)
declare_metric("vl_cluster_node_up", "gauge",
               "1 when the node answered the last usage poll, else 0",
               single_roll=True)
declare_metric("vl_cluster_stats_age_seconds", "gauge",
               "staleness of a node's last successful usage poll",
               single_roll=True)
declare_metric("vl_cluster_ingest_in_flight", "gauge",
               "worst-case (max across nodes) in-flight ingest rows "
               "per tenant from the ledger rollup", single_roll=True)
declare_metric("vl_cluster_ingest_dropped", "gauge",
               "worst-case (max across nodes) dropped ingest rows per "
               "tenant from the ledger rollup", single_roll=True)
declare_metric("vl_queries_cancel_propagated_total", "counter",
               "sub-queries cancelled via propagated cluster cancel "
               "(POST /internal/select/cancel)", single_roll=True)

# -- standing queries / per-part result cache (engine/standing/) --
declare_metric("vl_result_cache_hits_total", "counter",
               "per-part result cache hits (parts replayed without a "
               "dispatch)")
declare_metric("vl_result_cache_misses_total", "counter",
               "per-part result cache misses (parts that recomputed)")
declare_metric("vl_result_cache_evictions_total", "counter",
               "entries evicted at the VL_RESULT_CACHE_MAX_BYTES "
               "budget (LRU)")
declare_metric("vl_result_cache_stores_total", "counter",
               "entries stored at harvest/absorb")
declare_metric("vl_result_cache_bytes", "gauge",
               "bytes resident in the per-part result cache")
declare_metric("vl_result_cache_max_bytes", "gauge",
               "VL_RESULT_CACHE_MAX_BYTES budget")
declare_metric("vl_result_cache_entries", "gauge",
               "live (fingerprint, part uid) entries")
declare_metric("vl_standing_queries", "gauge",
               "registered standing-query fingerprints on this node")
declare_metric("vl_standing_subscribers", "gauge",
               "subscriber streams attached across all standing "
               "queries")
declare_metric("vl_standing_reevals_total", "counter",
               "standing-query re-evaluations (flush/merge-triggered "
               "+ registration seeds)")
declare_metric("vl_standing_pushes_dropped_total", "counter",
               "payload pushes dropped at a stalled subscriber's "
               "queue bound")

# -- histograms (obs/hist.py) --
declare_metric("vl_query_duration_seconds", "histogram",
               "end-to-end /select query execution time")
declare_metric("vl_tpu_dispatch_rtt_seconds", "histogram",
               "device dispatch round-trip time")
declare_metric("vl_tpu_host_sync_wait_seconds", "histogram",
               "host-side wait for device results")
declare_metric("vl_tpu_emit_seconds", "histogram",
               "harvest emit phase wall time")
declare_metric("vl_tpu_pack_size_parts", "histogram",
               "parts folded per packed super-dispatch")
declare_metric("vl_tpu_bloom_prune_ratio", "histogram",
               "fraction of blocks killed by bloom pruning")
declare_metric("vl_sched_queue_wait_seconds", "histogram",
               "admission queue wait")
declare_metric("vl_sched_slot_wait_seconds", "histogram",
               "dispatch-slot lease wait")
declare_metric("vl_net_first_frame_seconds", "histogram",
               "cluster sub-query time to first frame")
declare_metric("vl_cost_model_rel_error_duration", "histogram",
               "cost-model relative error: predicted vs actual "
               "duration")
declare_metric("vl_cost_model_rel_error_bytes", "histogram",
               "cost-model relative error: predicted vs actual bytes")
declare_metric("vl_cost_model_rel_error_dispatches", "histogram",
               "cost-model relative error: predicted vs actual "
               "dispatch count")
declare_metric("vl_ingest_freshness_seconds", "histogram",
               "in-memory residency of rows at flush: flush time minus "
               "the flushed parts' oldest creation time")
declare_metric("vl_ingest_to_queryable_seconds", "histogram",
               "accept wall clock to rows queryable (storage "
               "must_add return), observed per batch")
