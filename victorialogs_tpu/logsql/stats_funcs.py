"""LogsQL stats functions.

Mirrors the reference statsFunc/statsProcessor contract (lib/logstorage/
pipe_stats.go:73-125): per-group mutable state with update / merge /
export_state / import_state / finalize.  merge and export/import exist for
the cluster + multi-chip paths: device partials and remote-node partials are
merged into one state before finalize (the reference ships exported states
over HTTP; we additionally reduce numeric partials over ICI psum).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field

from .matchers import parse_number


def format_number(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "inf" if v > 0 else "-inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 2**63:
        return str(int(v))
    return repr(float(v))


class StatsFunc:
    """Base: parsed stats function with its input fields and result name."""

    name = "?"
    iff = None  # optional per-func row guard: `count() if (filter)`

    def __init__(self, fields: list[str], out_name: str = ""):
        self.fields = fields
        self.out_name = out_name or self.default_name()

    def default_name(self) -> str:
        args = ", ".join(self.fields)
        return f"{self.name}({args})"

    def to_string(self) -> str:
        s = f"{self.name}({', '.join(self.fields)})"
        if self.iff is not None:
            s += f" if ({self.iff.to_string()})"
        if self.out_name != self.default_name():
            s += f" as {self.out_name}"
        return s

    def needed_fields(self) -> set:
        out = set(self.fields)
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    # state protocol
    def new_state(self):
        raise NotImplementedError

    def block_cols(self, br):
        """Column lists this function consumes from a block (cached by the
        stats processor once per block)."""
        return [br.column(f) for f in self.fields]

    def update(self, state, cols: list[list[str]], idxs) -> None:
        """cols: whatever block_cols() returned for the current block."""
        raise NotImplementedError

    # memory accounting: the stats processor installs a shared budget and
    # accumulating funcs charge it on ACTUAL state growth (reference fails
    # queries at a fraction of memory.Allowed() — pipe_stats.go:314-348)
    budget = None

    def _charge(self, nbytes: int) -> None:
        if self.budget is not None and nbytes:
            self.budget.add(nbytes)

    def merge(self, a, b):
        raise NotImplementedError

    def finalize(self, state) -> str:
        raise NotImplementedError

    def export_state(self, state):
        return state

    def import_state(self, data):
        return data


class StatsCount(StatsFunc):
    name = "count"

    def default_name(self):
        return "count(*)" if not self.fields else super().default_name()

    def new_state(self):
        return 0

    def update(self, state, cols, idxs):
        if not self.fields:
            return state + len(idxs)
        n = state
        for i in idxs:
            if any(c[i] != "" for c in cols):
                n += 1
        return n

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return str(state)


class StatsCountEmpty(StatsFunc):
    name = "count_empty"

    def new_state(self):
        return 0

    def update(self, state, cols, idxs):
        n = state
        for i in idxs:
            if all(c[i] == "" for c in cols):
                n += 1
        return n

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return str(state)


class StatsSum(StatsFunc):
    name = "sum"

    def new_state(self):
        return math.nan

    def block_cols(self, br):
        # typed numeric columns skip per-row parsing entirely
        out = []
        for f in self.fields:
            num = br.numeric_column(f) \
                if hasattr(br, "numeric_column") else None
            out.append(num if num is not None else br.column(f))
        return out

    def update(self, state, cols, idxs):
        import numpy as np
        s = state
        for c in cols:
            if isinstance(c, np.ndarray):
                sub = c if len(idxs) == c.shape[0] else c[idxs]
                # produced numeric views (math results) may carry NaN for
                # non-numeric rows: skip them exactly like the string path
                nanmask = np.isnan(sub)
                if nanmask.any():
                    sub = sub[~nanmask]
                if sub.size:
                    add = float(np.sum(sub))
                    s = add if math.isnan(s) else s + add
                continue
            # same per-block pairwise summation as the array branch, so
            # typed and string paths produce bit-identical float sums
            buf = [v for i in idxs
                   if c[i] and not math.isnan(v := parse_number(c[i]))]
            if buf:
                add = float(np.sum(np.asarray(buf, dtype=np.float64)))
                s = add if math.isnan(s) else s + add
        return s

    def merge(self, a, b):
        if math.isnan(a):
            return b
        if math.isnan(b):
            return a
        return a + b

    def finalize(self, state):
        return format_number(state) if not math.isnan(state) else "NaN"


class StatsSumLen(StatsFunc):
    name = "sum_len"

    def new_state(self):
        return 0

    def update(self, state, cols, idxs):
        s = state
        for c in cols:
            for i in idxs:
                s += len(c[i])
        return s

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return str(state)


def _min_max_reduce(vals, want_min: bool, best: str | None = None):
    """Reference min/max selection over string values: skip empties,
    numeric-first ordering with string tiebreak (shared by the plain
    per-row paths and the lazy-column fallbacks)."""
    for v in vals:
        if v == "":
            continue
        if best is None or (_num_or_str_less(v, best) if want_min
                            else _num_or_str_less(best, v)):
            best = v
    return best


def _num_or_str_less(a: str, b: str) -> bool:
    """Reference lessString semantics: numeric compare when both parse."""
    fa, fb = parse_number(a), parse_number(b)
    if not math.isnan(fa) and not math.isnan(fb):
        if fa != fb:
            return fa < fb
        return a < b
    if not math.isnan(fa):
        return True   # numbers sort before strings
    if not math.isnan(fb):
        return False
    return a < b


class _LazyMinMaxCol:
    """Deferred typed view for min/max: numeric columns consult the block
    HEADER min/max first and decode only when the block can actually
    improve the running state; dict columns reduce over their (<=8)
    distinct values via the stored codes.  Either way no per-row string
    list materializes (reference typed columns with per-column min/max
    skips — block_result.go:26-63,2149-2199).

    Numeric soundness: uint/int/float encodings are round-trip exact, so
    numeric selection maps back to the same strings the per-row path
    would pick, and equal numbers can't be distinct strings."""
    __slots__ = ("br", "name", "is_dict")

    def __init__(self, br, name, is_dict: bool):
        self.br = br
        self.name = name
        self.is_dict = is_dict

    def candidate(self, idxs, want_min: bool) -> str | None:
        """Extreme among the selected rows as the stored string."""
        import numpy as np

        def str_reduce(vals) -> str | None:
            return _min_max_reduce(vals, want_min)

        if self.is_dict:
            dc = self.br.dict_column(self.name)
            if dc is None:
                # another consumer materialized the column between
                # block_cols and update: reduce the string list instead
                # of silently dropping the values
                vals = self.br.column(self.name)
                return str_reduce(vals[i] for i in idxs)
            ids, dvals = dc
            sub = ids if len(idxs) == ids.shape[0] else ids[idxs]
            if not sub.size:
                return None
            return str_reduce(dvals[j] for j in np.unique(sub))
        tn = self.br.typed_numeric(self.name)
        if tn is None:
            # same materialization race as above: string fallback
            vals = self.br.column(self.name)
            return str_reduce(vals[i] for i in idxs)
        arr, is_int = tn
        sub = arr if len(idxs) == arr.shape[0] else arr[idxs]
        if not sub.size:
            return None
        m = sub.min() if want_min else sub.max()
        if is_int:
            return str(int(m))
        from ..storage.values_encoder import _format_floats
        return str(_format_floats(np.array([m]))[0])


def _min_max_block_cols(fn, br):
    out = []
    for f in fn.fields:
        if hasattr(br, "header_min_max"):
            if br.header_min_max(f) is not None:
                out.append(_LazyMinMaxCol(br, f, is_dict=False))
                continue
            if br.dict_column(f) is not None:
                out.append(_LazyMinMaxCol(br, f, is_dict=True))
                continue
        out.append(br.column(f))
    return out


class StatsMin(StatsFunc):
    name = "min"

    def new_state(self):
        return None

    def block_cols(self, br):
        return _min_max_block_cols(self, br)

    def update(self, state, cols, idxs):
        best = state
        for c in cols:
            if isinstance(c, _LazyMinMaxCol):
                if not c.is_dict and best is not None:
                    # hdr can go None if another consumer materialized
                    # the column meanwhile (same race candidate handles)
                    hdr = c.br.header_min_max(c.name)
                    fb = parse_number(best)
                    # the block header min bounds any row subset: once the
                    # state is strictly below it, this block can't improve
                    # the min and the column is never read/decoded.
                    # STRICT compare: numeric ties must decode so the
                    # string tiebreak (_num_or_str_less) stays authoritative
                    if hdr is not None and not math.isnan(fb) and \
                            fb < hdr[0]:
                        continue
                got = c.candidate(idxs, want_min=True)
                if got is not None and (best is None or
                                        _num_or_str_less(got, best)):
                    best = got
                continue
            best = _min_max_reduce((c[i] for i in idxs), True, best)
        return best

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a if _num_or_str_less(a, b) else b

    def finalize(self, state):
        return state if state is not None else ""


class StatsMax(StatsMin):
    name = "max"

    def update(self, state, cols, idxs):
        best = state
        for c in cols:
            if isinstance(c, _LazyMinMaxCol):
                if not c.is_dict and best is not None:
                    hdr = c.br.header_min_max(c.name)
                    fb = parse_number(best)
                    # strict for the same tie reason as min
                    if hdr is not None and not math.isnan(fb) and \
                            fb > hdr[1]:
                        continue
                got = c.candidate(idxs, want_min=False)
                if got is not None and (best is None or
                                        _num_or_str_less(best, got)):
                    best = got
                continue
            best = _min_max_reduce((c[i] for i in idxs), False, best)
        return best

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return b if _num_or_str_less(a, b) else a


class StatsAvg(StatsFunc):
    name = "avg"

    def new_state(self):
        return (0.0, 0)  # (sum, count)

    block_cols = StatsSum.block_cols

    def update(self, state, cols, idxs):
        import numpy as np
        s, n = state
        for c in cols:
            if isinstance(c, np.ndarray):
                sub = c if len(idxs) == c.shape[0] else c[idxs]
                nanmask = np.isnan(sub)
                if nanmask.any():
                    sub = sub[~nanmask]
                s += float(np.sum(sub))
                n += int(sub.size)
                continue
            buf = [v for i in idxs
                   if c[i] and not math.isnan(v := parse_number(c[i]))]
            if buf:
                s += float(np.sum(np.asarray(buf, dtype=np.float64)))
                n += len(buf)
        return (s, n)

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, state):
        s, n = state
        return format_number(s / n) if n else "NaN"


class StatsCountUniq(StatsFunc):
    name = "count_uniq"

    def __init__(self, fields, out_name="", limit: int = 0):
        super().__init__(fields, out_name)
        self.limit = limit

    def new_state(self):
        return set()

    def block_cols(self, br):
        # typed lazy shapes (exact type: the hash subclass walks rows):
        # a block-constant column (consts, _stream, _stream_id) is ONE
        # candidate value; a dict column is at most its <=8 code values
        if type(self) is StatsCountUniq and len(self.fields) == 1 and \
                hasattr(br, "const_value"):
            f = self.fields[0]
            v = br.const_value(f)
            if v is not None:
                return [("__const__", v)]
            dc = br.dict_column(f)
            if dc is not None:
                return [("__dict__", dc)]
        return [br.column(f) for f in self.fields]

    def update(self, state, cols, idxs):
        if self.limit and len(state) >= self.limit:
            return state
        if len(cols) == 1 and isinstance(cols[0], tuple):
            import numpy as np
            kind, payload = cols[0]
            if kind == "__const__":
                if len(idxs) and payload != "" and \
                        (payload,) not in state:
                    state.add((payload,))
                    self._charge(len(payload) + 64)
                return state
            ids, dvals = payload
            sub = ids if len(idxs) == ids.shape[0] else ids[list(idxs)]
            for j in np.unique(sub):
                v = dvals[j]
                if v != "" and (v,) not in state:
                    state.add((v,))
                    self._charge(len(v) + 64)
            return state
        if len(cols) == 1:
            # single-field fast path: set ops run at C speed (the common
            # `count_uniq(field)` shape; dominated the stats bench config)
            vals = cols[0]
            if len(idxs) == len(vals):
                cand = {(v,) for v in vals if v != ""}
            else:
                cand = {(vals[i],) for i in idxs if vals[i] != ""}
            new = cand - state
            if new:
                self._charge(sum(len(k[0]) for k in new) + 64 * len(new))
                state |= new
            return state
        grown = 0
        for i in idxs:
            key = tuple(c[i] for c in cols)
            if any(k != "" for k in key) and key not in state:
                state.add(key)
                grown += sum(len(k) for k in key) + 64
        self._charge(grown)
        return state

    def merge(self, a, b):
        a |= b
        return a

    def finalize(self, state):
        n = len(state)
        if self.limit and n > self.limit:
            n = self.limit
        return str(n)

    def export_state(self, state):
        return sorted(state)

    def import_state(self, data):
        return set(tuple(x) for x in data)


class StatsCountUniqHash(StatsCountUniq):
    """Approximate-by-hash count of unique values (reference
    stats_count_uniq_hash.go): stores 64-bit hashes instead of values."""

    name = "count_uniq_hash"

    def update(self, state, cols, idxs):
        from ..utils.hashing import xxh64
        if self.limit and len(state) >= self.limit:
            return state
        for i in idxs:
            key = tuple(c[i] for c in cols)
            if any(k != "" for k in key):
                state.add(xxh64("\x00".join(key).encode("utf-8")))
        return state

    def import_state(self, data):
        return set(data)


class StatsUniqValues(StatsFunc):
    name = "uniq_values"

    def __init__(self, fields, out_name="", limit: int = 0):
        super().__init__(fields, out_name)
        self.limit = limit

    def new_state(self):
        return set()

    def update(self, state, cols, idxs):
        grown = 0
        for c in cols:
            for i in idxs:
                v = c[i]
                if v != "" and v not in state:
                    state.add(v)
                    grown += len(v) + 56
        self._charge(grown)
        return state

    def merge(self, a, b):
        a |= b
        return a

    def finalize(self, state):
        import json
        vals = sorted(state, key=lambda v: ((0, parse_number(v))
                                            if not math.isnan(parse_number(v))
                                            else (1, 0), v))
        if self.limit and len(vals) > self.limit:
            vals = vals[:self.limit]
        return json.dumps(vals, separators=(",", ":")) if vals else ""

    def export_state(self, state):
        return sorted(state)

    def import_state(self, data):
        return set(data)


class StatsValues(StatsFunc):
    name = "values"

    def __init__(self, fields, out_name="", limit: int = 0):
        super().__init__(fields, out_name)
        self.limit = limit

    def new_state(self):
        return []

    def update(self, state, cols, idxs):
        grown = 0
        for c in cols:
            for i in idxs:
                state.append(c[i])
                grown += len(c[i]) + 48
        self._charge(grown)
        return state

    def merge(self, a, b):
        a.extend(b)
        return a

    def finalize(self, state):
        import json
        vals = state
        if self.limit and len(vals) > self.limit:
            vals = vals[:self.limit]
        return json.dumps(vals, separators=(",", ":")) if vals else ""


class StatsQuantile(StatsFunc):
    name = "quantile"

    def __init__(self, phi: float, fields, out_name=""):
        self.phi = phi
        super().__init__(fields, out_name)

    def default_name(self):
        return f"quantile({format_number(self.phi)}, {', '.join(self.fields)})"

    def to_string(self):
        s = f"quantile({format_number(self.phi)}, {', '.join(self.fields)})"
        if self.out_name != self.default_name():
            s += f" as {self.out_name}"
        return s

    def new_state(self):
        return []

    def update(self, state, cols, idxs):
        grown = 0
        for c in cols:
            for i in idxs:
                v = parse_number(c[i]) if c[i] else math.nan
                if not math.isnan(v):
                    state.append(v)
                    grown += 32
        self._charge(grown)
        return state

    def merge(self, a, b):
        a.extend(b)
        return a

    def finalize(self, state):
        if not state:
            return "NaN"
        vs = sorted(state)
        idx = int(self.phi * len(vs))
        if idx >= len(vs):
            idx = len(vs) - 1
        return format_number(vs[idx])


class StatsMedian(StatsQuantile):
    name = "median"

    def __init__(self, fields, out_name=""):
        super().__init__(0.5, fields, out_name)

    def default_name(self):
        return f"median({', '.join(self.fields)})"

    def to_string(self):
        s = f"median({', '.join(self.fields)})"
        if self.out_name != self.default_name():
            s += f" as {self.out_name}"
        return s


# ---------------- histogram (VictoriaMetrics-style vmrange buckets) -------

_HIST_BUCKETS_PER_DECIMAL = 18
_HIST_LOWER = 1e-9
_HIST_UPPER = 1e18


def _vmrange(v: float) -> str:
    """Log-scale bucket label for v (18 buckets per decade, the
    VictoriaMetrics histogram layout — reference stats_histogram.go)."""
    if v < _HIST_LOWER:
        return f"0...{_HIST_LOWER:.3e}"
    if v > _HIST_UPPER:
        return f"{_HIST_UPPER:.3e}...+Inf"
    idx = math.floor(math.log10(v) * _HIST_BUCKETS_PER_DECIMAL + 1e-9)
    lo = 10 ** (idx / _HIST_BUCKETS_PER_DECIMAL)
    hi = 10 ** ((idx + 1) / _HIST_BUCKETS_PER_DECIMAL)
    if v > hi:  # float rounding at bucket edges
        idx += 1
        lo, hi = hi, 10 ** ((idx + 1) / _HIST_BUCKETS_PER_DECIMAL)
    return f"{lo:.3e}...{hi:.3e}"


def _vmrange_sort_key(r: str):
    try:
        return float(r.split("...", 1)[0])
    except ValueError:
        return math.inf


class StatsHistogram(StatsFunc):
    name = "histogram"

    def new_state(self):
        return {}

    def update(self, state, cols, idxs):
        for c in cols:
            for i in idxs:
                v = parse_number(c[i]) if c[i] else math.nan
                if math.isnan(v) or v < 0:
                    continue
                r = _vmrange(v)
                state[r] = state.get(r, 0) + 1
        return state

    def merge(self, a, b):
        for k, v in b.items():
            a[k] = a.get(k, 0) + v
        return a

    def finalize(self, state):
        import json
        out = [{"vmrange": r, "hits": state[r]}
               for r in sorted(state, key=_vmrange_sort_key)]
        return json.dumps(out, separators=(",", ":"))


# ---------------- rate / rate_sum ----------------

class StatsRate(StatsCount):
    """count() divided by the query's time-filter range in seconds
    (reference stats_rate.go; step set via Query time filter —
    parser.go:1218-1224)."""

    name = "rate"
    step_seconds: float = 0.0

    def finalize(self, state):
        v = float(state)
        if self.step_seconds > 0:
            v /= self.step_seconds
        return format_number(v)


class StatsRateSum(StatsSum):
    name = "rate_sum"
    step_seconds: float = 0.0

    def finalize(self, state):
        if math.isnan(state):
            return "NaN"
        v = state
        if self.step_seconds > 0:
            v /= self.step_seconds
        return format_number(v)


# ---------------- row_min / row_max / json_values ----------------

class StatsRowMin(StatsFunc):
    """Captures the whole row (or named fields) where src_field is minimal
    (reference stats_row_min.go)."""

    name = "row_min"
    _want_max = False

    def __init__(self, fields, out_name=""):
        if not fields:
            raise ValueError(f"{self.name} needs a source field")
        self.src_field = fields[0]
        self.row_fields = fields[1:]
        super().__init__(fields, out_name)

    def needed_fields(self):
        if self.row_fields:
            return {self.src_field, *self.row_fields}
        return {"*"}

    def block_cols(self, br):
        src = br.column(self.src_field)
        names = self.row_fields or br.column_names()
        return [src, [(n, br.column(n)) for n in names]]

    def new_state(self):
        return None  # (src_value, row_dict)

    def _better(self, a: str, b: str) -> bool:
        return _num_or_str_less(b, a) if self._want_max \
            else _num_or_str_less(a, b)

    def update(self, state, cols, idxs):
        src, row_cols = cols
        best = state
        for i in idxs:
            v = src[i]
            if v == "":
                continue
            if best is None or self._better(v, best[0]):
                best = (v, {n: c[i] for n, c in row_cols if c[i] != ""})
        return best

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a if self._better(a[0], b[0]) else b

    def finalize(self, state):
        import json
        return json.dumps(state[1], separators=(",", ":")) \
            if state is not None else ""

    def export_state(self, state):
        return state

    def import_state(self, data):
        return tuple(data) if data is not None else None


class StatsRowMax(StatsRowMin):
    name = "row_max"
    _want_max = True


class StatsJSONValues(StatsFunc):
    """Per-row JSON objects collected into one JSON array (reference
    stats_json_values.go)."""

    name = "json_values"

    def __init__(self, fields, out_name="", limit: int = 0):
        super().__init__(fields, out_name)
        self.limit = limit

    def needed_fields(self):
        return set(self.fields) if self.fields else {"*"}

    def block_cols(self, br):
        names = self.fields or br.column_names()
        return [[(n, br.column(n)) for n in names]]

    def new_state(self):
        return []

    def update(self, state, cols, idxs):
        import json
        if self.limit and len(state) >= self.limit:
            return state
        row_cols = cols[0]
        grown = 0
        for i in idxs:
            item = json.dumps({n: c[i] for n, c in row_cols},
                              separators=(",", ":"), ensure_ascii=False)
            state.append(item)
            grown += len(item) + 48
            if self.limit and len(state) >= self.limit:
                break
        self._charge(grown)
        return state

    def merge(self, a, b):
        a.extend(b)
        return a

    def finalize(self, state):
        items = state
        if self.limit and len(items) > self.limit:
            items = items[:self.limit]
        return "[" + ",".join(items) + "]"


class StatsRowAny(StatsFunc):
    name = "row_any"

    def default_name(self):
        return "row_any(*)" if not self.fields else super().default_name()

    def needed_fields(self):
        out = super().needed_fields()
        return out if self.fields else out | {"*"}

    def new_state(self):
        return None

    def block_cols(self, br):
        # with no named fields, capture the whole row (reference row_any)
        if self.fields:
            return [(f, br.column(f)) for f in self.fields]
        return [(n, br.column(n)) for n in br.column_names()]

    def update(self, state, cols, idxs):
        if state is not None or not idxs:
            return state
        i = idxs[0]
        return {f: c[i] for f, c in cols if c[i] != ""}

    def merge(self, a, b):
        return a if a is not None else b

    def finalize(self, state):
        import json
        return json.dumps(state, separators=(",", ":")) \
            if state is not None else ""
