"""LogsQL transform pipes: extract/format/math/unpack/replace/top/... .

The second half of the reference pipe registry (lib/logstorage/pipe.go:
119-386) — row-transforming pipes built on the same streaming Processor
contract as pipes.py.  All of them are stateless per-block transforms except
`top`, `field_names` and `field_values`, which accumulate and emit at flush.

Each pipe supports the reference's optional `if (filter)` guard where the
reference does (pipe_extract.go:135-143 pattern: rows failing the guard pass
through unchanged)."""

from __future__ import annotations

import base64
import binascii
import json
import math
import random
import re
import time as _time
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..engine.block_result import BlockResult
from .duration import parse_duration
from .lexer import Lexer, quote_token_if_needed
from .matchers import parse_number
from .pipes import (ParseError, Pipe, Processor, _parse_field_name,
                    _parse_uint, register_pipe)


# ---------------- shared helpers ----------------

def parse_if_filter(lex: Lexer):
    """Parse `if (filter)` — 'if' already current token."""
    lex.next_token()
    if not lex.is_keyword("("):
        raise ParseError("missing '(' after if")
    lex.next_token()
    from .parser import parse_filter_or
    f = parse_filter_or(lex, "")
    if not lex.is_keyword(")"):
        raise ParseError("missing ')' after if filter")
    lex.next_token()
    return f


def _maybe_if(lex: Lexer):
    if lex.is_keyword("if"):
        return parse_if_filter(lex)
    return None


def _if_str(iff) -> str:
    return f" if ({iff.to_string()})" if iff is not None else ""


def _if_mask(iff, br: BlockResult):
    if iff is None:
        return None
    return iff.apply_to_values(br.column, br.nrows)


def _parse_compound_arg(lex: Lexer) -> str:
    from .parser import _get_compound_token
    return _get_compound_token(lex, stop=(",", "(", ")", "|", ""))


# ---------------- pattern engine (reference pattern.go) ----------------

@dataclass
class PatternStep:
    prefix: str
    field: str = ""
    opt: str = ""


_HTML_UNESCAPES = {"&lt;": "<", "&gt;": ">", "&amp;": "&",
                   "&quot;": '"', "&apos;": "'"}


def _html_unescape(s: str) -> str:
    for k, v in _HTML_UNESCAPES.items():
        s = s.replace(k, v)
    return s


class Pattern:
    """'text<field>text...' extraction pattern (reference pattern.go:1-251).

    Greedy-less matching: each unquoted field matches up to the next literal
    prefix; `<q:field>` tries Go-unquoting first; prefixes between fields
    must be non-empty; prefixes support &lt;/&gt; escapes."""

    def __init__(self, pattern_str: str):
        self.pattern_str = pattern_str
        self.steps = self._parse_steps(pattern_str)
        if not any(st.field for st in self.steps):
            raise ParseError(
                f"pattern {pattern_str!r} needs at least one <field>")
        for i in range(1, len(self.steps)):
            if not self.steps[i].prefix:
                raise ParseError(
                    f"missing delimiter between <{self.steps[i-1].field}> "
                    f"and <{self.steps[i].field}>")
        self.fields = [st.field for st in self.steps if st.field]

    @staticmethod
    def _parse_steps(s: str) -> list:
        steps = []
        i, n = 0, len(s)
        prefix = []
        while i < n:
            c = s[i]
            if c != "<":
                prefix.append(c)
                i += 1
                continue
            j = s.find(">", i + 1)
            if j < 0:
                prefix.append(c)
                i += 1
                continue
            name = s[i + 1:j]
            opt = ""
            if ":" in name:
                opt, name = name.split(":", 1)
                opt = opt.strip()
            name = name.strip()
            if name == "_":
                name = ""        # <_> is an anonymous skip like <>
            steps.append(PatternStep(_html_unescape("".join(prefix)),
                                     name, opt))
            prefix = []
            i = j + 1
        if prefix:
            steps.append(PatternStep(_html_unescape("".join(prefix))))
        if steps and not steps[0].prefix and not steps[0].field and \
                len(steps) > 1:
            steps = steps[1:]
        return steps

    def apply(self, s: str) -> dict:
        """Extract fields from s; mismatch => all fields empty."""
        out = {f: "" for f in self.fields}
        steps = self.steps
        idx = s.find(steps[0].prefix) if steps[0].prefix else 0
        if idx < 0:
            return out
        s = s[idx + len(steps[0].prefix):]
        for i, st in enumerate(steps):
            nxt = steps[i + 1].prefix if i + 1 < len(steps) else ""
            if st.opt != "plain":
                us, off = _try_unquote_prefix(s)
                if off >= 0:
                    if st.field:
                        out[st.field] = us
                    s = s[off:]
                    if not s.startswith(nxt):
                        # mid-pattern mismatch keeps fields extracted so
                        # far (reference pattern.apply — pattern.go:125)
                        return out
                    s = s[len(nxt):]
                    continue
            if not nxt:
                if st.field:
                    out[st.field] = s
                return out
            pos = s.find(nxt)
            if pos < 0:
                return out
            if st.field:
                out[st.field] = s[:pos]
            s = s[pos + len(nxt):]
        return out


def _try_unquote_prefix(s: str):
    """Go strconv.QuotedPrefix + Unquote; returns (value, consumed|-1)."""
    if not s or s[0] not in "\"`":
        return "", -1
    q = s[0]
    if q == "`":
        j = s.find("`", 1)
        if j < 0:
            return "", -1
        return s[1:j], j + 1
    i, n = 1, len(s)
    out = []
    while i < n:
        c = s[i]
        if c == '"':
            return "".join(out), i + 1
        if c == "\\" and i + 1 < n:
            e = s[i + 1]
            mapping = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                       '"': '"', "'": "'", "a": "\a", "b": "\b",
                       "f": "\f", "v": "\v", "/": "/"}
            if e in mapping:
                out.append(mapping[e])
                i += 2
                continue
            if e == "x" and i + 3 < n:
                try:
                    out.append(chr(int(s[i + 2:i + 4], 16)))
                    i += 4
                    continue
                except ValueError:
                    return "", -1
            if e == "u" and i + 5 < n:
                try:
                    out.append(chr(int(s[i + 2:i + 6], 16)))
                    i += 6
                    continue
                except ValueError:
                    return "", -1
            return "", -1
        out.append(c)
        i += 1
    return "", -1


# ---------------- extract / extract_regexp ----------------

def _merge_extracted(br, out_cols, names, mask, keep_original, skip_empty):
    """Apply keep_original_fields / skip_empty_results / if-mask merging."""
    for name in names:
        newv = out_cols[name]
        if keep_original or skip_empty or mask is not None:
            orig = br.column(name) if br.has_column(name) else [""] * br.nrows
            for i in range(br.nrows):
                if mask is not None and not mask[i]:
                    newv[i] = orig[i]
                elif keep_original and orig[i] != "":
                    newv[i] = orig[i]
                elif skip_empty and newv[i] == "" and orig[i] != "":
                    newv[i] = orig[i]


@dataclass(repr=False)
class PipeExtract(Pipe):
    pattern_str: str
    from_field: str = "_msg"
    keep_original_fields: bool = False
    skip_empty_results: bool = False
    iff: object = None

    name = "extract"

    def __post_init__(self):
        self.ptn = Pattern(self.pattern_str)

    def to_string(self):
        s = "extract" + _if_str(self.iff) + " " + \
            quote_token_if_needed(self.pattern_str)
        if self.from_field != "_msg":
            s += " from " + quote_token_if_needed(self.from_field)
        if self.keep_original_fields:
            s += " keep_original_fields"
        if self.skip_empty_results:
            s += " skip_empty_results"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {self.from_field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def output_fields(self):
        return list(self.ptn.fields)

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                vals = br.column(pipe.from_field)
                out_cols = {f: [""] * br.nrows for f in pipe.ptn.fields}
                prev_v, prev = None, None
                for i in range(br.nrows):
                    if mask is not None and not mask[i]:
                        continue
                    v = vals[i]
                    if v != prev_v:
                        prev_v, prev = v, pipe.ptn.apply(v)
                    for f in pipe.ptn.fields:
                        out_cols[f][i] = prev[f]
                _merge_extracted(br, out_cols, pipe.ptn.fields, mask,
                                 pipe.keep_original_fields,
                                 pipe.skip_empty_results)
                out = br.materialize()
                for f in pipe.ptn.fields:
                    out._cols[f] = out_cols[f]
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeExtractRegexp(Pipe):
    pattern_str: str
    from_field: str = "_msg"
    keep_original_fields: bool = False
    skip_empty_results: bool = False
    iff: object = None

    name = "extract_regexp"

    def __post_init__(self):
        self.re = re.compile(self.pattern_str)
        self.fields = [g for g in self.re.groupindex]
        if not self.fields:
            raise ParseError(
                "extract_regexp needs at least one named group "
                "(?P<name>...)")

    def to_string(self):
        s = "extract_regexp" + _if_str(self.iff) + " " + \
            quote_token_if_needed(self.pattern_str)
        if self.from_field != "_msg":
            s += " from " + quote_token_if_needed(self.from_field)
        if self.keep_original_fields:
            s += " keep_original_fields"
        if self.skip_empty_results:
            s += " skip_empty_results"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {self.from_field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                vals = br.column(pipe.from_field)
                out_cols = {f: [""] * br.nrows for f in pipe.fields}
                for i in range(br.nrows):
                    if mask is not None and not mask[i]:
                        continue
                    m = pipe.re.search(vals[i])
                    if m is None:
                        continue
                    for f in pipe.fields:
                        out_cols[f][i] = m.group(f) or ""
                _merge_extracted(br, out_cols, pipe.fields, mask,
                                 pipe.keep_original_fields,
                                 pipe.skip_empty_results)
                out = br.materialize()
                for f in pipe.fields:
                    out._cols[f] = out_cols[f]
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- format ----------------

def _format_duration(ns: float) -> str:
    """Go time.Duration.String() rendering (the reference formats
    durations with Go's stdlib — e.g. 210123456789ns -> '3m30.123456789s',
    1500µs -> '1.5ms')."""
    if math.isnan(ns):
        return ""
    n = int(ns)
    if n == 0:
        return "0s"
    sign = "-" if n < 0 else ""
    n = abs(n)

    def frac(val: int, digits: int) -> str:
        s = f"{val:0{digits}d}".rstrip("0")
        return "." + s if s else ""

    if n < 1000:
        return f"{sign}{n}ns"
    if n < 10**6:
        return f"{sign}{n // 1000}{frac(n % 1000, 3)}µs"
    if n < 10**9:
        return f"{sign}{n // 10**6}{frac(n % 10**6, 6)}ms"
    secs, sub = divmod(n, 10**9)
    out = f"{secs % 60}{frac(sub, 9)}s"
    mins = secs // 60
    if mins:
        out = f"{mins % 60}m" + out
        hours = mins // 60
        if hours:
            out = f"{hours}h" + out
    return sign + out


def _format_value(v: str, opt: str) -> str:
    """Apply a format option (reference pipe_format.go:180-250)."""
    if opt in ("", "plain"):
        return v
    if opt == "q":
        return json.dumps(v, ensure_ascii=False)
    if opt == "uc":
        return v.upper()
    if opt == "lc":
        return v.lower()
    if opt == "hexencode":
        return v.encode("utf-8").hex().upper()
    if opt == "hexdecode":
        try:
            return bytes.fromhex(v).decode("utf-8", "replace")
        except ValueError:
            return v
    if opt == "hexnumencode":
        try:
            n = int(v)
            if not 0 <= n < 2**64:
                return v
        except ValueError:
            return v
        return f"{n:016X}"
    if opt == "hexnumdecode":
        if 0 < len(v) <= 16:
            try:
                return str(int(v, 16))
            except ValueError:
                return v
        return v
    if opt == "base64encode":
        return base64.b64encode(v.encode("utf-8")).decode()
    if opt == "base64decode":
        try:
            return base64.b64decode(v, validate=True).decode("utf-8",
                                                             "replace")
        except (ValueError, binascii.Error):
            return v
    if opt == "urlencode":
        from urllib.parse import quote
        return quote(v, safe="")
    if opt == "urldecode":
        from urllib.parse import unquote
        return unquote(v)
    if opt == "duration":
        n = parse_number(v)
        return _format_duration(n) if not math.isnan(n) else v
    if opt == "duration_seconds":
        d = parse_duration(v)
        return str(d // 10**9) if d is not None else v
    if opt == "ipv4":
        n = parse_number(v)
        if math.isnan(n) or not 0 <= n <= 2**32 - 1:
            return v
        n = int(n)
        return f"{(n >> 24) & 255}.{(n >> 16) & 255}." \
               f"{(n >> 8) & 255}.{n & 255}"
    if opt == "time":
        ns = _parse_unix_timestamp_ns(v)
        if ns is None:
            return v
        from ..engine.block_result import format_rfc3339
        return format_rfc3339(ns)
    return v


def _parse_unix_timestamp_ns(v: str) -> int | None:
    """Unix timestamp (secs/millis/micros/nanos, optional decimal
    fraction) -> int64 ns without float precision loss (reference
    timeutil.TryParseUnixTimestamp)."""
    s = v.strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    whole, _, fraction = s.partition(".")
    if not whole.isdigit() or (fraction and not fraction.isdigit()):
        return None
    n = int(whole)
    if fraction:                      # decimal seconds
        scale = 9
    elif n < 10**11:
        scale = 9                     # seconds
    elif n < 10**14:
        scale = 6                     # millis
    elif n < 10**17:
        scale = 3                     # micros
    else:
        scale = 0                     # nanos
    frac_ns = int((fraction + "0" * scale)[:scale] or "0")
    ns = n * 10**scale + frac_ns
    return -ns if neg else ns


@dataclass(repr=False)
class PipeFormat(Pipe):
    format_str: str
    result_field: str = "_msg"
    keep_original_fields: bool = False
    skip_empty_results: bool = False
    iff: object = None

    name = "format"

    def __post_init__(self):
        self.steps = Pattern._parse_steps(self.format_str)

    def to_string(self):
        s = "format" + _if_str(self.iff) + " " + \
            quote_token_if_needed(self.format_str)
        if self.result_field != "_msg":
            s += " as " + quote_token_if_needed(self.result_field)
        if self.keep_original_fields:
            s += " keep_original_fields"
        if self.skip_empty_results:
            s += " skip_empty_results"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {st.field for st in self.steps if st.field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                cols = {st.field: br.column(st.field)
                        for st in pipe.steps if st.field}
                orig = br.column(pipe.result_field) \
                    if br.has_column(pipe.result_field) else [""] * br.nrows
                out_vals = []
                for i in range(br.nrows):
                    if mask is not None and not mask[i]:
                        out_vals.append(orig[i])
                        continue
                    buf = []
                    for st in pipe.steps:
                        buf.append(st.prefix)
                        if st.field:
                            buf.append(_format_value(cols[st.field][i],
                                                     st.opt))
                    v = "".join(buf)
                    if (pipe.keep_original_fields or
                            (pipe.skip_empty_results and v == "")) and \
                            orig[i] != "":
                        v = orig[i]
                    out_vals.append(v)
                out = br.materialize()
                out._cols[pipe.result_field] = out_vals
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- math ----------------

def _math_num(s: str) -> float:
    """Reference parseMathNumber order: number (incl. 0x hex), duration,
    IPv4, RFC3339 timestamp (pipe_math.go:1066)."""
    if s[:2].lower() == "0x":
        try:
            return float(int(s, 16))
        except ValueError:
            pass
    v = parse_number(s)
    if not math.isnan(v):
        return v
    d = parse_duration(s)
    if d is not None:
        return float(d)
    from .matchers import parse_ipv4
    ip = parse_ipv4(s)
    if ip is not None:
        return float(ip)
    from ..engine.block_result import parse_rfc3339
    t = parse_rfc3339(s)
    if t is not None:
        return float(t)
    return math.nan


def _to_u64(v: float) -> int:
    if math.isnan(v) or math.isinf(v):
        return 0
    return int(v) & (2**64 - 1)


_MATH_BINOPS = {
    "^": (1, lambda a, b: math.nan if (math.isnan(a) or math.isnan(b))
          else _safe_pow(a, b)),
    "*": (2, lambda a, b: a * b),
    "/": (2, lambda a, b: a / b if b else math.nan),
    "%": (2, lambda a, b: math.fmod(a, b) if b else math.nan),
    "+": (3, lambda a, b: a + b),
    "-": (3, lambda a, b: a - b),
    "&": (4, lambda a, b: float(_to_u64(a) & _to_u64(b))),
    "xor": (5, lambda a, b: float(_to_u64(a) ^ _to_u64(b))),
    "or": (6, lambda a, b: float(_to_u64(a) | _to_u64(b))),
    "default": (10, lambda a, b: b if math.isnan(a) else a),
}


def _safe_pow(a, b):
    try:
        r = a ** b
        return r if isinstance(r, (int, float)) else math.nan
    except (OverflowError, ValueError, ZeroDivisionError):
        return math.nan


def _m_round(args):
    if len(args) == 1:
        v = args[0]
        return float(round(v)) if not math.isnan(v) else v
    v, nearest = args[0], args[1]
    if math.isnan(v) or math.isnan(nearest) or nearest == 0:
        return math.nan
    return round(v / nearest) * nearest


_MATH_FUNCS = {
    "abs": (1, lambda a: abs(a[0])),
    "exp": (1, lambda a: _safe_pow(math.e, a[0])),
    "ln": (1, lambda a: math.log(a[0]) if a[0] > 0 else math.nan),
    "max": (-1, lambda a: max(a) if a else math.nan),
    "min": (-1, lambda a: min(a) if a else math.nan),
    "round": (-2, _m_round),
    "ceil": (1, lambda a: float(math.ceil(a[0]))
             if not (math.isnan(a[0]) or math.isinf(a[0])) else a[0]),
    "floor": (1, lambda a: float(math.floor(a[0]))
              if not (math.isnan(a[0]) or math.isinf(a[0])) else a[0]),
    "now": (0, lambda a: float(_time.time_ns())),
    "rand": (0, lambda a: random.random()),
}


class MathExpr:
    def __init__(self, kind, value=None, args=None, op=None):
        self.kind = kind          # const | field | func | binop
        self.value = value
        self.args = args or []
        self.op = op

    def needed_fields(self) -> set:
        if self.kind == "field":
            return {self.value}
        out = set()
        for a in self.args:
            out |= a.needed_fields()
        return out

    def eval_row(self, get, i) -> float:
        k = self.kind
        if k == "const":
            return self.value
        if k == "field":
            return _math_num(get(self.value)[i])
        vals = [a.eval_row(get, i) for a in self.args]
        if k == "func":
            try:
                return _MATH_FUNCS[self.op][1](vals)
            except (ValueError, OverflowError):
                return math.nan
        fn = _MATH_BINOPS[self.op][1]
        try:
            return fn(vals[0], vals[1])
        except (ValueError, OverflowError, ZeroDivisionError):
            return math.nan

    # vectorizable core: const/field/{+,-,*,/} over storage-typed numeric
    # columns — identical IEEE semantics to the per-row path (division by
    # zero maps to NaN exactly like eval_row).  Anything else returns
    # None and the per-row interpreter runs.
    _VEC_OPS = {"+", "-", "*", "/"}

    def eval_vec(self, br, n, produced=None):
        k = self.kind
        if k == "const":
            return np.full(n, float(self.value))
        if k == "field":
            if produced and self.value in produced:
                # an earlier entry (re)wrote this field: its vec result,
                # or None when it took the row path (bail to rows too)
                return produced[self.value]
            if not hasattr(br, "numeric_column"):
                return None
            return br.numeric_column(self.value)
        if k == "binop" and self.op in self._VEC_OPS:
            a = self.args[0].eval_vec(br, n, produced)
            if a is None:
                return None
            b = self.args[1].eval_vec(br, n, produced)
            if b is None:
                return None
            with np.errstate(all="ignore"):
                if self.op == "+":
                    return a + b
                if self.op == "-":
                    return a - b
                if self.op == "*":
                    return a * b
                return np.where(b == 0.0, np.nan, a / b)
        return None

    def to_string(self) -> str:
        if self.kind == "const":
            from .stats_funcs import format_number
            return format_number(self.value)
        if self.kind == "field":
            return quote_token_if_needed(self.value)
        if self.kind == "func":
            return f"{self.op}({', '.join(a.to_string() for a in self.args)})"
        return f"({self.args[0].to_string()} {self.op} " \
               f"{self.args[1].to_string()})"


def parse_math_expr(lex: Lexer) -> MathExpr:
    left = _parse_math_operand(lex)
    return _parse_math_binop(lex, left, 20)


def _parse_math_binop(lex: Lexer, left: MathExpr, max_prio: int) -> MathExpr:
    while True:
        op = lex.token.lower()
        if op not in _MATH_BINOPS:
            return left
        prio = _MATH_BINOPS[op][0]
        if prio > max_prio:
            return left
        lex.next_token()
        right = _parse_math_operand(lex)
        # bind tighter ops on the right first
        while True:
            nop = lex.token.lower()
            if nop in _MATH_BINOPS and _MATH_BINOPS[nop][0] < prio:
                right = _parse_math_binop(lex, right,
                                          _MATH_BINOPS[nop][0])
                continue
            break
        left = MathExpr("binop", args=[left, right], op=op)


def _parse_math_operand(lex: Lexer) -> MathExpr:
    tok = lex.token
    low = tok.lower()
    if lex.is_keyword("("):
        lex.next_token()
        e = parse_math_expr(lex)
        if not lex.is_keyword(")"):
            raise ParseError("missing ')' in math expr")
        lex.next_token()
        return e
    if low in _MATH_FUNCS:
        lex.next_token()
        if not lex.is_keyword("("):
            # field named like a function
            return MathExpr("field", value=tok)
        lex.next_token()
        args = []
        while not lex.is_keyword(")"):
            if lex.is_keyword(","):
                lex.next_token()
                continue
            args.append(parse_math_expr(lex))
        lex.next_token()
        arity = _MATH_FUNCS[low][0]
        if arity >= 0 and len(args) != arity:
            raise ParseError(f"{low}() expects {arity} args")
        if arity == -2 and not 1 <= len(args) <= 2:
            raise ParseError(f"{low}() expects 1 or 2 args")
        if arity == -1 and not args:
            raise ParseError(f"{low}() expects at least one arg")
        return MathExpr("func", args=args, op=low)
    if lex.is_keyword("-"):
        lex.next_token()
        inner = _parse_math_operand(lex)
        if inner.kind == "const":
            return MathExpr("const", value=-inner.value)
        return MathExpr("binop", args=[MathExpr("const", value=0.0), inner],
                        op="-")
    if lex.is_keyword("+"):
        lex.next_token()
        return _parse_math_operand(lex)
    quoted = getattr(lex, "is_quoted", False)
    v = _math_num(tok) if tok else math.nan
    if tok and not math.isnan(v) and (quoted or tok[0].isdigit() or
                                      tok[0] in ".-+" or
                                      low in ("inf", "nan")):
        # consts: numbers (incl. 0x/size suffixes), durations, and quoted
        # IPv4/timestamp values like '2024-05-30T01:02:03Z'
        lex.next_token()
        return MathExpr("const", value=v)
    # field operand: a SINGLE token — compound gluing would swallow
    # operators like the '+' in `b+1`
    if not tok or tok in (",", ")", "|", "(", "as"):
        raise ParseError(f"bad math operand near {tok!r}")
    lex.next_token()
    return MathExpr("field", value=tok)


@dataclass(repr=False)
class PipeMath(Pipe):
    entries: list  # [(MathExpr, result_field)]

    name = "math"

    def to_string(self):
        return "math " + ", ".join(
            f"{e.to_string()} as {quote_token_if_needed(r)}"
            for e, r in self.entries)

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = set()
        for e, _r in self.entries:
            out |= e.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                from .stats_funcs import format_number
                out = br.materialize()

                def get(name):
                    return out.column(name) if out.has_column(name) \
                        else [""] * out.nrows
                produced: dict = {}
                for expr, res in pipe.entries:
                    vec = expr.eval_vec(br, br.nrows, produced)
                    if vec is not None:
                        vals = [
                            "NaN" if math.isnan(v) else format_number(v)
                            for v in vec.tolist()]
                        out._cols[res] = vals
                        out._num_cols[res] = (vals, vec)
                    else:
                        vals = []
                        for i in range(br.nrows):
                            v = expr.eval_row(get, i)
                            vals.append("NaN" if math.isnan(v)
                                        else format_number(v))
                        out._cols[res] = vals
                    produced[res] = vec
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- unpack_json / unpack_logfmt / unpack_syslog ----------------

def _flatten_json(obj, prefix="") -> list:
    """Flatten a JSON object into (path, scalar-string) pairs the way the
    reference unpacks (nested keys joined with '.')."""
    out = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                out.extend(_flatten_json(v, key))
            elif isinstance(v, list):
                out.append((key, json.dumps(v, separators=(",", ":"),
                                            ensure_ascii=False)))
            elif isinstance(v, bool):
                out.append((key, "true" if v else "false"))
            elif v is None:
                out.append((key, ""))
            elif isinstance(v, str):
                out.append((key, v))
            else:
                from .stats_funcs import format_number
                out.append((key, format_number(v)
                            if isinstance(v, float) else str(v)))
    return out


def parse_logfmt(s: str) -> list:
    """k=v pairs with Go-quoted values (reference logfmt_parser.go).

    Reference edge semantics (logfmt_parser_test.go): a bare word becomes
    a key with an empty value; a bare `=value` goes to `_msg`."""
    out = []
    i, n = 0, len(s)
    while i < n:
        while i < n and s[i] == " ":
            i += 1
        if i >= n:
            break
        j = i
        while j < n and s[j] not in " =":
            j += 1
        key = s[i:j]
        if j >= n or s[j] == " ":
            out.append((key, ""))       # bare word: empty value
            i = j
            continue
        if not key:
            key = "_msg"                # `=value` with no key
        i = j + 1
        if i < n and s[i] in "\"`":
            v, off = _try_unquote_prefix(s[i:])
            if off >= 0:
                out.append((key, v))
                i += off
                continue
        sp = s.find(" ", i)
        if sp < 0:
            sp = n
        out.append((key, s[i:sp]))
        i = sp
    return out


class _UnpackBase(Pipe):
    """Shared unpack scaffolding: from-field, field filter, result_prefix,
    keep_original_fields/skip_empty_results, if-guard."""

    def __init__(self, from_field="_msg", fields=None, result_prefix="",
                 keep_original_fields=False, skip_empty_results=False,
                 iff=None):
        self.from_field = from_field
        self.fields = fields or []
        self.result_prefix = result_prefix
        self.keep_original_fields = keep_original_fields
        self.skip_empty_results = skip_empty_results
        self.iff = iff

    def _unpack_value(self, v: str) -> list:
        raise NotImplementedError

    def to_string(self):
        s = self.name + _if_str(self.iff)
        if self.from_field != "_msg":
            s += " from " + quote_token_if_needed(self.from_field)
        if self.fields:
            s += " fields (" + ", ".join(self.fields) + ")"
        if self.result_prefix:
            s += " result_prefix " + quote_token_if_needed(self.result_prefix)
        if self.keep_original_fields:
            s += " keep_original_fields"
        if self.skip_empty_results:
            s += " skip_empty_results"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {self.from_field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self
        allow = set(pipe.fields) or None
        allow_prefixes = tuple(f[:-1] for f in pipe.fields
                               if f.endswith("*")) if allow else ()

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                vals = br.column(pipe.from_field)
                out_cols: dict[str, list] = {}
                prev_v, prev = None, None
                for i in range(br.nrows):
                    if mask is not None and not mask[i]:
                        continue
                    v = vals[i]
                    if v != prev_v:
                        prev_v, prev = v, pipe._unpack_value(v)
                    for k, val in prev:
                        if allow is not None and k not in allow and \
                                not (allow_prefixes and
                                     k.startswith(allow_prefixes)):
                            continue
                        key = pipe.result_prefix + k
                        col = out_cols.get(key)
                        if col is None:
                            col = out_cols[key] = [""] * br.nrows
                        col[i] = val
                names = list(out_cols)
                _merge_extracted(br, out_cols, names, mask,
                                 pipe.keep_original_fields,
                                 pipe.skip_empty_results)
                out = br.materialize()
                for k in names:
                    out._cols[k] = out_cols[k]
                self.next_p.write_block(out)
        return P(next_p)


class PipeUnpackJson(_UnpackBase):
    name = "unpack_json"

    def _unpack_value(self, v):
        try:
            obj = json.loads(v)
        except (ValueError, RecursionError):
            return []
        return _flatten_json(obj) if isinstance(obj, dict) else []


class PipeUnpackLogfmt(_UnpackBase):
    name = "unpack_logfmt"

    def _unpack_value(self, v):
        return parse_logfmt(v)


class PipeUnpackSyslog(_UnpackBase):
    name = "unpack_syslog"

    def __init__(self, *args, offset_ns=0, **kw):
        super().__init__(*args, **kw)
        self.offset_ns = offset_ns

    def to_string(self):
        s = super().to_string()
        if self.offset_ns:
            # render offset right after the from clause like the reference
            s += f" offset {self.offset_ns // 3600_000_000_000}h"
        return s

    def _unpack_value(self, v):
        from ..server.syslog import parse_syslog_message
        fields = parse_syslog_message(v, tz_offset_ns=self.offset_ns)
        return [(k, val) for k, val in fields if k != "_msg"] + \
            [(k, val) for k, val in fields if k == "_msg" and val != v]


class PipeUnpackWords(_UnpackBase):
    """unpack_words: tokenize the field into a JSON array of words
    (reference pipe_unpack_words.go)."""

    name = "unpack_words"

    def __init__(self, from_field="_msg", dst_field="words",
                 drop_duplicates=False, iff=None):
        super().__init__(from_field=from_field, iff=iff)
        self.dst_field = dst_field
        self.drop_duplicates = drop_duplicates

    def to_string(self):
        s = "unpack_words"
        if self.from_field != "_msg":
            s += " from " + quote_token_if_needed(self.from_field)
        if self.dst_field != "words":
            s += " as " + quote_token_if_needed(self.dst_field)
        if self.drop_duplicates:
            s += " drop_duplicates"
        return s

    def _unpack_value(self, v):
        from ..utils.tokenizer import tokenize_string
        toks = tokenize_string(v)
        if self.drop_duplicates:
            toks = list(dict.fromkeys(toks))
        return [(self.dst_field,
                 json.dumps(toks, separators=(",", ":"),
                            ensure_ascii=False))]


# ---------------- replace / replace_regexp ----------------

@dataclass(repr=False)
class PipeReplace(Pipe):
    old: str
    new: str
    field: str = "_msg"
    limit: int = 0
    iff: object = None
    regexp: bool = False

    name = "replace"

    def __post_init__(self):
        if self.regexp:
            self._re = re.compile(self.old)

    def to_string(self):
        nm = "replace_regexp" if self.regexp else "replace"
        s = nm + _if_str(self.iff) + \
            f" ({quote_token_if_needed(self.old)}, " \
            f"{quote_token_if_needed(self.new)})"
        if self.field != "_msg":
            s += " at " + quote_token_if_needed(self.field)
        if self.limit:
            s += f" limit {self.limit}"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {self.field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                vals = br.column(pipe.field)
                limit = pipe.limit if pipe.limit > 0 else 0
                out_vals = []
                for i, v in enumerate(vals):
                    if mask is not None and not mask[i]:
                        out_vals.append(v)
                        continue
                    if pipe.regexp:
                        out_vals.append(pipe._re.sub(pipe.new, v,
                                                     count=limit))
                    else:
                        out_vals.append(v.replace(pipe.old, pipe.new,
                                                  limit or -1))
                out = br.materialize()
                out._cols[pipe.field] = out_vals
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- top ----------------

@dataclass(repr=False)
class PipeTop(Pipe):
    by: list
    limit: int = 10
    hits_field: str = "hits"
    rank_field: str = ""

    name = "top"

    def input_fields(self, out_needed):
        return set(self.by) if self.by else {"*"}

    def to_string(self):
        s = "top"
        if self.limit != 10:
            s += f" {self.limit}"
        if self.by:
            s += " by (" + ", ".join(self.by) + ")"
        if self.hits_field != "hits":
            s += " hits as " + quote_token_if_needed(self.hits_field)
        if self.rank_field:
            s += " rank as " + quote_token_if_needed(self.rank_field)
        return s

    def needed_fields(self):
        return set(self.by)

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                from ..utils.memory import MemoryBudget
                self.counts: dict[tuple, int] = {}
                self.budget = MemoryBudget(0.4, "top")

            def write_block(self, br):
                if len(pipe.by) == 1 and \
                        hasattr(br, "dict_value_counts"):
                    # typed fast path: const/dict columns count through
                    # their stored codes, no per-row Python
                    pairs = br.dict_value_counts(pipe.by[0])
                    if pairs is not None:
                        for v, cnt in pairs:
                            key = (v,)
                            if key not in self.counts:
                                self.counts[key] = cnt
                                self.budget.add(len(v) + 80)
                            else:
                                self.counts[key] += cnt
                        return
                if pipe.by:
                    cols = [br.column(f) for f in pipe.by]
                    keys = (tuple(c[i] for c in cols)
                            for i in range(br.nrows))
                else:
                    # keys carry (field, value) pairs so blocks with
                    # different column sets mix safely
                    names = br.column_names()
                    cols = [(f, br.column(f)) for f in names]
                    keys = (tuple((f, c[i]) for f, c in cols if c[i] != "")
                            for i in range(br.nrows))
                for key in keys:
                    if key not in self.counts:
                        self.counts[key] = 1
                        self.budget.add(sum(len(str(k)) for k in key) + 80)
                    else:
                        self.counts[key] += 1

            def flush(self):
                # hits desc, then key asc (reference pipe_top ordering)
                items = sorted(self.counts.items(),
                               key=lambda kv: (-kv[1], kv[0]))
                items = items[:pipe.limit]
                if pipe.by:
                    cols = {f: [k[j] for k, _ in items]
                            for j, f in enumerate(pipe.by)}
                else:
                    names: dict[str, None] = {}
                    for k, _h in items:
                        for f, _v in k:
                            names.setdefault(f, None)
                    cols = {f: [dict(k).get(f, "") for k, _ in items]
                            for f in names}
                cols[pipe.hits_field] = [str(h) for _, h in items]
                if pipe.rank_field:
                    cols[pipe.rank_field] = [str(i + 1)
                                             for i in range(len(items))]
                self.next_p.write_block(BlockResult.from_columns(cols)
                                        if items else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


# ---------------- drop_empty_fields / len / pack / sample / unroll ----------

@dataclass(repr=False)
class PipeDropEmptyFields(Pipe):
    name = "drop_empty_fields"

    def to_string(self):
        return "drop_empty_fields"

    def can_live_tail(self):
        return True

    def make_processor(self, next_p):
        class P(Processor):
            def write_block(self, br):
                out = br.materialize()
                # drop all-empty columns; drop rows with no non-empty field
                keep_cols = {n: v for n, v in out._cols.items()
                             if any(x != "" for x in v)}
                if len(keep_cols) != len(out._cols):
                    out._cols = keep_cols
                if keep_cols:
                    rows_mask = np.zeros(out.nrows, dtype=bool)
                    for v in keep_cols.values():
                        for i, x in enumerate(v):
                            if x != "":
                                rows_mask[i] = True
                    if not rows_mask.all():
                        out = out.filter_rows(rows_mask)
                elif out.nrows:
                    out = BlockResult(0)
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeLen(Pipe):
    field: str
    result_field: str = "_msg"

    name = "len"

    def to_string(self):
        s = f"len({quote_token_if_needed(self.field)})"
        if self.result_field != "_msg":
            s += " as " + quote_token_if_needed(self.result_field)
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return {self.field}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                vals = br.column(pipe.field)
                out = br.materialize()
                out._cols[pipe.result_field] = [
                    str(len(v.encode("utf-8"))) for v in vals]
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipePackJson(Pipe):
    result_field: str = "_msg"
    fields: list = dc_field(default_factory=list)
    logfmt: bool = False

    name = "pack_json"

    def to_string(self):
        s = "pack_logfmt" if self.logfmt else "pack_json"
        if self.fields:
            s += " fields (" + ", ".join(self.fields) + ")"
        if self.result_field != "_msg":
            s += " as " + quote_token_if_needed(self.result_field)
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return set(self.fields)

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                names = pipe.fields or br.column_names()
                cols = [(n, br.column(n)) for n in names]
                out_vals = []
                for i in range(br.nrows):
                    if pipe.logfmt:
                        parts = []
                        for n, c in cols:
                            v = c[i]
                            if re.search(r'[\s"=]', v) or v == "":
                                v = json.dumps(v, ensure_ascii=False)
                            parts.append(f"{n}={v}")
                        out_vals.append(" ".join(parts))
                    else:
                        out_vals.append(json.dumps(
                            {n: c[i] for n, c in cols},
                            separators=(",", ":"), ensure_ascii=False))
                out = br.materialize()
                out._cols[pipe.result_field] = out_vals
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeSample(Pipe):
    n: int

    name = "sample"

    def to_string(self):
        return f"sample {self.n}"

    def can_live_tail(self):
        return True

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.rng = random.Random()
                self.next_row = self._step() - 1
                self.seen = 0

            def _step(self):
                # expected-gap sampling: mean gap == n (pipe_sample.go)
                if pipe.n <= 1:
                    return 1
                return 1 + int(self.rng.uniform(0, 2 * (pipe.n - 1)))

            def write_block(self, br):
                if pipe.n <= 1:
                    self.next_p.write_block(br)
                    return
                keep = []
                lo = self.seen
                hi = self.seen + br.nrows
                while self.next_row < hi:
                    keep.append(self.next_row - lo)
                    self.next_row += self._step()
                self.seen = hi
                if keep:
                    mask = np.zeros(br.nrows, dtype=bool)
                    mask[keep] = True
                    self.next_p.write_block(br.filter_rows(mask))
        return P(next_p)


def unpack_json_array(v: str) -> list:
    try:
        arr = json.loads(v)
    except (ValueError, RecursionError):
        return []
    if not isinstance(arr, list):
        return []
    out = []
    for x in arr:
        if isinstance(x, str):
            out.append(x)
        elif isinstance(x, bool):
            out.append("true" if x else "false")
        elif x is None:
            out.append("")
        elif isinstance(x, (dict, list)):
            out.append(json.dumps(x, separators=(",", ":"),
                                  ensure_ascii=False))
        else:
            from .stats_funcs import format_number
            out.append(format_number(x) if isinstance(x, float) else str(x))
    return out


@dataclass(repr=False)
class PipeUnroll(Pipe):
    fields: list
    iff: object = None

    name = "unroll"

    def to_string(self):
        return "unroll" + _if_str(self.iff) + \
            " by (" + ", ".join(self.fields) + ")"

    def needed_fields(self):
        out = set(self.fields)
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                names = br.column_names()
                cols = {n: br.column(n) for n in names}
                out_cols: dict[str, list] = {n: [] for n in names}
                for n in pipe.fields:
                    out_cols.setdefault(n, [])
                for i in range(br.nrows):
                    if mask is not None and not mask[i]:
                        unrolled = {f: [cols.get(f, [""] * br.nrows)[i]]
                                    for f in pipe.fields}
                        count = 1
                    else:
                        unrolled = {
                            f: unpack_json_array(
                                cols.get(f, [""] * br.nrows)[i])
                            for f in pipe.fields}
                        count = max((len(v) for v in unrolled.values()),
                                    default=0) or 1
                    for k in range(count):
                        for n in out_cols:
                            if n in unrolled:
                                vs = unrolled[n]
                                out_cols[n].append(vs[k] if k < len(vs)
                                                   else "")
                            else:
                                out_cols[n].append(cols[n][i])
                self.next_p.write_block(
                    BlockResult.from_columns(out_cols)
                    if out_cols and any(out_cols.values())
                    else BlockResult(0))
        return P(next_p)


# ---------------- field_names / field_values / blocks_count ----------------

@dataclass(repr=False)
class PipeFieldNames(Pipe):
    result_name: str = "name"

    name = "field_names"

    def to_string(self):
        s = "field_names"
        if self.result_name != "name":
            s += " as " + quote_token_if_needed(self.result_name)
        return s

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.hits: dict[str, int] = {}

            def write_block(self, br):
                for n in br.column_names():
                    cnt = sum(1 for v in br.column(n) if v != "")
                    if n in ("_time", "_stream", "_stream_id"):
                        cnt = br.nrows
                    if cnt:
                        self.hits[n] = self.hits.get(n, 0) + cnt

            def flush(self):
                keys = sorted(self.hits)
                cols = {pipe.result_name: list(keys),
                        "hits": [str(self.hits[k]) for k in keys]}
                self.next_p.write_block(BlockResult.from_columns(cols)
                                        if keys else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


@dataclass(repr=False)
class PipeFieldValues(Pipe):
    field: str
    limit: int = 0

    name = "field_values"

    def input_fields(self, out_needed):
        return {self.field}

    def to_string(self):
        s = "field_values " + quote_token_if_needed(self.field)
        if self.limit:
            s += f" limit {self.limit}"
        return s

    def needed_fields(self):
        return {self.field}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.hits: dict[str, int] = {}

            def write_block(self, br):
                for v in br.column(pipe.field):
                    if v != "":
                        self.hits[v] = self.hits.get(v, 0) + 1

            def flush(self):
                keys = sorted(self.hits)
                if pipe.limit and len(keys) > pipe.limit:
                    keys = keys[:pipe.limit]
                cols = {pipe.field: list(keys),
                        "hits": [str(self.hits[k]) for k in keys]}
                self.next_p.write_block(BlockResult.from_columns(cols)
                                        if keys else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


@dataclass(repr=False)
class PipeFacets(Pipe):
    """Per-field top values with hit counts (reference pipe_facets.go:
    output columns field_name/field_value/hits)."""

    limit: int = 10
    max_values_per_field: int = 1000
    max_value_len: int = 1000
    keep_const_fields: bool = False

    name = "facets"

    def to_string(self):
        s = "facets"
        if self.limit != 10:
            s += f" {self.limit}"
        if self.max_values_per_field != 1000:
            s += f" max_values_per_field {self.max_values_per_field}"
        if self.max_value_len != 1000:
            s += f" max_value_len {self.max_value_len}"
        if self.keep_const_fields:
            s += " keep_const_fields"
        return s

    def input_fields(self, out_needed):
        return {"*"}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.counts: dict[str, dict[str, int]] = {}
                self.rows_total = 0

            def write_block(self, br):
                self.rows_total += br.nrows
                names = [n for n in br.column_names()
                         if n not in ("_time", "_stream_id", "_stream")]
                for n in names:
                    per = self.counts.setdefault(n, {})
                    if per is None:
                        continue
                    for v in br.column(n):
                        if v == "" or len(v) > pipe.max_value_len:
                            continue
                        if len(per) >= pipe.max_values_per_field and \
                                v not in per:
                            # too many distinct values: not a facet
                            self.counts[n] = None
                            break
                        per[v] = per.get(v, 0) + 1

            def flush(self):
                out = {"field_name": [], "field_value": [], "hits": []}
                for field in sorted(self.counts):
                    per = self.counts[field]
                    if per is None:
                        continue
                    if not pipe.keep_const_fields and len(per) == 1 and \
                            next(iter(per.values())) == self.rows_total:
                        continue  # constant field: not a useful facet
                    items = sorted(per.items(),
                                   key=lambda kv: (-kv[1], kv[0]))
                    for v, hits in items[:pipe.limit]:
                        out["field_name"].append(field)
                        out["field_value"].append(v)
                        out["hits"].append(str(hits))
                self.next_p.write_block(
                    BlockResult.from_columns(out)
                    if out["field_name"] else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


@dataclass(repr=False)
class PipeBlocksCount(Pipe):
    result_name: str = "blocks_count"

    name = "blocks_count"

    def input_fields(self, out_needed):
        return set()

    def to_string(self):
        s = "blocks_count"
        if self.result_name != "blocks_count":
            s += " as " + quote_token_if_needed(self.result_name)
        return s

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.blocks = 0

            def write_block(self, br):
                if br.nrows:
                    self.blocks += 1

            def flush(self):
                self.next_p.write_block(BlockResult.from_columns(
                    {pipe.result_name: [str(self.blocks)]}))
                self.next_p.flush()
        return P(next_p)


# ---------------- parsers + registration ----------------

def _parse_quoted_arg(lex: Lexer) -> str:
    from .parser import _get_compound_token
    return _get_compound_token(lex, stop=(",", ")", "|", ""))


def _parse_from_clause(lex: Lexer) -> str:
    if lex.is_keyword("from"):
        lex.next_token()
        return _parse_field_name(lex)
    return "_msg"


def _parse_unpack_opts(lex: Lexer, pipe) -> None:
    while True:
        if lex.is_keyword("result_prefix"):
            lex.next_token()
            pipe.result_prefix = _parse_field_name(lex)
        elif lex.is_keyword("keep_original_fields"):
            pipe.keep_original_fields = True
            lex.next_token()
        elif lex.is_keyword("skip_empty_results"):
            pipe.skip_empty_results = True
            lex.next_token()
        elif lex.is_keyword("fields"):
            lex.next_token()
            pipe.fields = _parse_paren_fields(lex)
        else:
            return


def _parse_paren_fields(lex: Lexer) -> list:
    if not lex.is_keyword("("):
        raise ParseError("missing '('")
    lex.next_token()
    out = []
    while not lex.is_keyword(")"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        name = _parse_field_name(lex)
        if lex.is_keyword("*") and not lex.is_skipped_space:
            name += "*"          # wildcard: `fields (req_*)`
            lex.next_token()
        out.append(name)
    lex.next_token()
    return out


def _parse_extract(lex: Lexer):
    iff = _maybe_if(lex)
    pattern = _parse_quoted_arg(lex)
    p = PipeExtract(pattern, iff=iff)
    p.from_field = _parse_from_clause(lex)
    _parse_unpack_opts(lex, p)
    return p


def _parse_extract_regexp(lex: Lexer):
    iff = _maybe_if(lex)
    pattern = _parse_quoted_arg(lex)
    p = PipeExtractRegexp(pattern, iff=iff)
    p.from_field = _parse_from_clause(lex)
    _parse_unpack_opts(lex, p)
    return p


def _parse_format(lex: Lexer):
    iff = _maybe_if(lex)
    fmt = _parse_quoted_arg(lex)
    p = PipeFormat(fmt, iff=iff)
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_field = _parse_field_name(lex)
    while True:
        if lex.is_keyword("keep_original_fields"):
            p.keep_original_fields = True
            lex.next_token()
        elif lex.is_keyword("skip_empty_results"):
            p.skip_empty_results = True
            lex.next_token()
        else:
            break
    return p


def _parse_math(lex: Lexer):
    entries = []
    while True:
        expr = parse_math_expr(lex)
        if lex.is_keyword("as"):
            lex.next_token()
            res = _parse_field_name(lex)
            if not res:
                raise ParseError("math: missing result field after 'as'")
        elif lex.is_keyword(",", "|", ")") or lex.is_end():
            # optional result name: default to the expression rendering
            # (reference allows `math a / b default c`)
            res = expr.to_string()
        else:
            res = _parse_field_name(lex)
            if not res:
                raise ParseError(
                    "math: missing result field after expression")
        entries.append((expr, res))
        if lex.is_keyword(","):
            lex.next_token()
            continue
        break
    return PipeMath(entries)


def _parse_unpack_json(lex: Lexer):
    iff = _maybe_if(lex)
    p = PipeUnpackJson(iff=iff)
    p.from_field = _parse_from_clause(lex)
    _parse_unpack_opts(lex, p)
    return p


def _parse_unpack_logfmt(lex: Lexer):
    iff = _maybe_if(lex)
    p = PipeUnpackLogfmt(iff=iff)
    p.from_field = _parse_from_clause(lex)
    _parse_unpack_opts(lex, p)
    return p


def _parse_unpack_syslog(lex: Lexer):
    iff = _maybe_if(lex)
    p = PipeUnpackSyslog(iff=iff)
    p.from_field = _parse_from_clause(lex)
    if lex.is_keyword("offset"):
        lex.next_token()
        d = parse_duration(lex.token)
        if d is None:
            raise ParseError(f"bad unpack_syslog offset {lex.token!r}")
        p.offset_ns = d
        lex.next_token()
    _parse_unpack_opts(lex, p)
    return p


def _parse_unpack_words(lex: Lexer):
    iff = _maybe_if(lex)
    p = PipeUnpackWords(iff=iff)
    p.from_field = _parse_from_clause(lex)
    if lex.is_keyword("as"):
        lex.next_token()
        p.dst_field = _parse_field_name(lex)
    if lex.is_keyword("drop_duplicates"):
        p.drop_duplicates = True
        lex.next_token()
    return p


def _parse_replace(lex: Lexer, regexp: bool):
    iff = _maybe_if(lex)
    if not lex.is_keyword("("):
        raise ParseError("missing '(' after replace")
    lex.next_token()
    old = _parse_quoted_arg(lex)
    if not lex.is_keyword(","):
        raise ParseError("replace needs (old, new)")
    lex.next_token()
    new = _parse_quoted_arg(lex)
    if not lex.is_keyword(")"):
        raise ParseError("missing ')' after replace args")
    lex.next_token()
    p = PipeReplace(old, new, iff=iff, regexp=regexp)
    if lex.is_keyword("at"):
        lex.next_token()
        p.field = _parse_field_name(lex)
    if lex.is_keyword("limit"):
        lex.next_token()
        p.limit = _parse_uint(lex, "limit")
    if regexp:
        p.__post_init__()
    return p


def _parse_top(lex: Lexer):
    limit = 10
    if not lex.is_keyword("by", "(") and not lex.is_end() and \
            not lex.is_keyword("|") and lex.token.isdigit():
        limit = _parse_uint(lex, "top limit")
    by = []
    if lex.is_keyword("by"):
        lex.next_token()
    if lex.is_keyword("("):
        by = _parse_paren_fields(lex)
    elif not lex.is_end() and not lex.is_keyword("|", "hits", "rank"):
        # bare field list: `top b hits abc` (reference parsePipeTop)
        while True:
            by.append(_parse_field_name(lex))
            if lex.is_keyword(","):
                lex.next_token()
                continue
            break
    p = PipeTop(by, limit=limit)
    while True:
        if lex.is_keyword("hits"):
            lex.next_token()
            if lex.is_keyword("as"):
                lex.next_token()
            p.hits_field = _parse_field_name(lex)
        elif lex.is_keyword("rank"):
            lex.next_token()
            if lex.is_keyword("as"):
                lex.next_token()
            if lex.is_end() or lex.is_keyword("|"):
                p.rank_field = "rank"     # bare `rank`
            else:
                p.rank_field = _parse_field_name(lex)
        else:
            break
    return p


def _parse_len(lex: Lexer):
    if not lex.is_keyword("("):
        raise ParseError("missing '(' after len")
    lex.next_token()
    fld = _parse_field_name(lex)
    if not lex.is_keyword(")"):
        raise ParseError("missing ')' after len field")
    lex.next_token()
    p = PipeLen(fld)
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_field = _parse_field_name(lex)
    elif not lex.is_end() and not lex.is_keyword("|"):
        p.result_field = _parse_field_name(lex)
    return p


def _parse_pack(lex: Lexer, logfmt: bool):
    p = PipePackJson(logfmt=logfmt)
    if lex.is_keyword("fields"):
        lex.next_token()
        p.fields = _parse_paren_fields(lex)
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_field = _parse_field_name(lex)
    elif not lex.is_end() and not lex.is_keyword("|"):
        p.result_field = _parse_field_name(lex)
    return p


def _parse_sample(lex: Lexer):
    n = _parse_uint(lex, "sample")
    if n < 1:
        raise ParseError("sample must be >= 1")
    return PipeSample(n)


def _parse_unroll(lex: Lexer):
    iff = _maybe_if(lex)
    if lex.is_keyword("by"):
        lex.next_token()
    fields = _parse_paren_fields(lex)
    if not fields:
        raise ParseError("unroll needs at least one field")
    return PipeUnroll(fields, iff=iff)


def _parse_field_names(lex: Lexer):
    p = PipeFieldNames()
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_name = _parse_field_name(lex)
    return p


def _parse_field_values(lex: Lexer):
    fld = _parse_field_name(lex)
    p = PipeFieldValues(fld)
    if lex.is_keyword("limit"):
        lex.next_token()
        p.limit = _parse_uint(lex, "limit")
    return p


def _parse_facets(lex: Lexer):
    p = PipeFacets()
    if not lex.is_end() and not lex.is_keyword("|") and \
            lex.token.isdigit():
        p.limit = _parse_uint(lex, "facets limit")
    while True:
        if lex.is_keyword("max_values_per_field"):
            lex.next_token()
            p.max_values_per_field = _parse_uint(lex,
                                                 "max_values_per_field")
        elif lex.is_keyword("max_value_len"):
            lex.next_token()
            p.max_value_len = _parse_uint(lex, "max_value_len")
        elif lex.is_keyword("keep_const_fields"):
            p.keep_const_fields = True
            lex.next_token()
        else:
            break
    return p


def _parse_blocks_count(lex: Lexer):
    p = PipeBlocksCount()
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_name = _parse_field_name(lex)
    return p


def _parse_drop_empty_fields(lex: Lexer):
    return PipeDropEmptyFields()


register_pipe("extract", _parse_extract)
register_pipe("extract_regexp", _parse_extract_regexp)
register_pipe("format", _parse_format)
register_pipe("fmt", _parse_format)     # reference alias
register_pipe("math", _parse_math)
register_pipe("eval", _parse_math)
register_pipe("unpack_json", _parse_unpack_json)
register_pipe("unpack_logfmt", _parse_unpack_logfmt)
register_pipe("unpack_syslog", _parse_unpack_syslog)
register_pipe("unpack_words", _parse_unpack_words)
register_pipe("replace", lambda lex: _parse_replace(lex, regexp=False))
register_pipe("replace_regexp", lambda lex: _parse_replace(lex, regexp=True))
register_pipe("top", _parse_top)
register_pipe("len", _parse_len)
register_pipe("pack_json", lambda lex: _parse_pack(lex, logfmt=False))
register_pipe("pack_logfmt", lambda lex: _parse_pack(lex, logfmt=True))
register_pipe("sample", _parse_sample)
register_pipe("unroll", _parse_unroll)
register_pipe("facets", _parse_facets)
register_pipe("field_names", _parse_field_names)
register_pipe("field_values", _parse_field_values)
register_pipe("blocks_count", _parse_blocks_count)
register_pipe("drop_empty_fields", _parse_drop_empty_fields)
