"""LogsQL parser: query -> (options, filter tree, pipes).

Grammar and semantics mirror the reference hand-written parser
(lib/logstorage/parser.go): implicit AND between adjacent filters,
`or`/`and`/`not`(`!`/`-`) operators, parenthesized groups, `field:filter`
scoping, compound phrases glued from adjacent unspaced tokens, `{...}` stream
filters, `_time:` filters, and the trailing `| pipe | pipe ...` chain.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field

from ..storage.stream_filter import StreamFilter, TagFilter
from .duration import NS, parse_duration, ts_bounds
from .filters import (Filter, FilterAnd, FilterAnyCasePhrase,
                      FilterAnyCasePrefix, FilterContainsAll,
                      FilterContainsAny, FilterDayRange, FilterEqField,
                      FilterExact, FilterExactPrefix, FilterIn,
                      FilterIPv4Range, FilterLeField, FilterLenRange,
                      FilterNoop, FilterNot, FilterOr, FilterPhrase,
                      FilterPrefix, FilterRange, FilterRegexp, FilterSequence,
                      FilterStream, FilterStreamID, FilterStringRange,
                      FilterTime, FilterValueType, FilterWeekRange)
from .lexer import Lexer, quote_token_if_needed
from .matchers import parse_ipv4, parse_number

MAX_TS = (1 << 63) - 1
MIN_TS = -(1 << 63)


class ParseError(ValueError):
    pass


@dataclass
class QueryOptions:
    concurrency: int = 0
    ignore_global_time_filter: bool = False


@dataclass
class Query:
    filter: Filter
    pipes: list = dc_field(default_factory=list)
    opts: QueryOptions = dc_field(default_factory=QueryOptions)
    timestamp: int | None = None

    def to_string(self) -> str:
        s = self.filter.to_string()
        for p in self.pipes:
            s += f" | {p.to_string()}"
        return s

    def get_time_range(self) -> tuple[int, int]:
        """Overall [min_ts, max_ts] from top-level AND-ed time filters."""
        return _filter_time_range(self.filter)

    def add_time_filter(self, start_ns: int, end_ns: int) -> None:
        tf = FilterTime(min_ts=start_ns, max_ts=end_ns)
        f = self.filter
        if isinstance(f, FilterAnd):
            f.filters.insert(0, tf)
        else:
            self.filter = FilterAnd([tf, f])

    def add_pipe_limit(self, n: int) -> None:
        from .pipes import PipeLimit
        self.pipes.append(PipeLimit(n))

    def get_concurrency(self) -> int:
        if self.opts.concurrency > 0:
            return self.opts.concurrency
        import os
        return min(os.cpu_count() or 1, 16)

    def clone(self, timestamp: int | None = None) -> "Query":
        q = parse_query(self.to_string(),
                        timestamp if timestamp is not None
                        else self.timestamp)
        return q

    def can_return_last_n_results(self) -> bool:
        """True when `| sort by (_time) desc | limit N` tail-opt applies."""
        from .pipes import (PipeFields, PipeLimit, PipeOffset, PipeSort)
        for p in self.pipes:
            if not isinstance(p, (PipeSort, PipeLimit, PipeOffset,
                                  PipeFields)):
                return False
        return True

    def can_live_tail(self) -> bool:
        for p in self.pipes:
            if not p.can_live_tail():
                return False
        return True

    def has_stats_pipe(self) -> bool:
        from .pipes import PipeStats
        return any(isinstance(p, PipeStats) for p in self.pipes)


def _filter_time_range(f: Filter) -> tuple[int, int]:
    if isinstance(f, FilterTime):
        return f.min_ts, f.max_ts
    if isinstance(f, FilterAnd):
        lo, hi = MIN_TS, MAX_TS
        for sub in f.filters:
            slo, shi = _filter_time_range(sub)
            lo = max(lo, slo)
            hi = min(hi, shi)
        return lo, hi
    if isinstance(f, FilterOr):
        lo, hi = MAX_TS, MIN_TS
        for sub in f.filters:
            slo, shi = _filter_time_range(sub)
            lo = min(lo, slo)
            hi = max(hi, shi)
        if lo > hi:
            return MIN_TS, MAX_TS
        return lo, hi
    return MIN_TS, MAX_TS


def parse_query(s: str, timestamp: int | None = None) -> Query:
    lex = Lexer(s, timestamp=timestamp)
    q = _parse_query_internal(lex)
    if not lex.is_end():
        raise ParseError(f"unexpected trailing token {lex.token!r} "
                         f"near ...{lex.context()}")
    return q


def parse_filter_string(s: str) -> Filter:
    """Parse a standalone filter expression (extra_filters etc.)."""
    lex = Lexer(s)
    f = parse_filter_or(lex, "")
    if not lex.is_end():
        raise ParseError(f"unexpected trailing token {lex.token!r}")
    return f


def _parse_query_internal(lex: Lexer) -> Query:
    opts = QueryOptions()
    if lex.is_keyword("options"):
        opts = _parse_options(lex)
    f = parse_filter_or(lex, "")
    pipes = []
    from .pipes import parse_pipes
    if lex.is_keyword("|"):
        lex.next_token()
        pipes = parse_pipes(lex)
    return Query(filter=f, pipes=pipes, opts=opts, timestamp=lex.timestamp)


def _parse_options(lex: Lexer) -> QueryOptions:
    opts = QueryOptions()
    lex.next_token()
    if not lex.is_keyword("("):
        raise ParseError("missing '(' after options")
    lex.next_token()
    while not lex.is_keyword(")"):
        name = lex.token
        lex.next_token()
        if not lex.is_keyword("="):
            raise ParseError(f"missing '=' after option {name!r}")
        lex.next_token()
        value = lex.token
        lex.next_token()
        if name == "concurrency":
            opts.concurrency = int(value)
        elif name == "ignore_global_time_filter":
            opts.ignore_global_time_filter = value.lower() == "true"
        else:
            raise ParseError(f"unknown query option {name!r}")
        if lex.is_keyword(","):
            lex.next_token()
    lex.next_token()
    return opts


# ---------------- filter grammar ----------------

def parse_filter_or(lex: Lexer, field_name: str) -> Filter:
    filters = [parse_filter_and(lex, field_name)]
    while True:
        if lex.is_keyword("or"):
            lex.next_token()
            filters.append(parse_filter_and(lex, field_name))
        else:
            break
    if len(filters) == 1:
        return filters[0]
    return FilterOr(filters)


def parse_filter_and(lex: Lexer, field_name: str) -> Filter:
    filters = [parse_generic_filter(lex, field_name)]
    while True:
        if lex.is_end() or lex.is_keyword("or", "|", ")", "]", ","):
            break
        if lex.is_keyword("and"):
            lex.next_token()
        filters.append(parse_generic_filter(lex, field_name))
    if len(filters) == 1:
        return filters[0]
    return FilterAnd(filters)


def parse_generic_filter(lex: Lexer, field_name: str) -> Filter:
    if lex.is_keyword("{"):
        if field_name not in ("", "_stream"):
            raise ParseError("stream filter can only apply to _stream")
        return _parse_filter_stream(lex)
    if lex.is_keyword(":"):
        lex.next_token()
        return parse_generic_filter(lex, field_name)
    if lex.is_keyword("*"):
        lex.next_token()
        return FilterPrefix(field_name, "") if field_name else FilterNoop()
    if lex.is_keyword("("):
        return _parse_parens(lex, field_name)
    if lex.is_keyword(">"):
        return _parse_gt(lex, field_name)
    if lex.is_keyword("<"):
        return _parse_lt(lex, field_name)
    if lex.is_keyword("="):
        return _parse_eq(lex, field_name)
    if lex.is_keyword("!="):
        lex.next_token()
        return FilterNot(_parse_eq_tail(lex, field_name))
    if lex.is_keyword("~"):
        lex.next_token()
        return _parse_regexp_tail(lex, field_name)
    if lex.is_keyword("!~"):
        lex.next_token()
        return FilterNot(_parse_regexp_tail(lex, field_name))
    if lex.is_keyword("not", "!", "-"):
        lex.next_token()
        return FilterNot(parse_generic_filter(lex, field_name))
    for kw, fn in _FUNC_FILTERS.items():
        if lex.is_keyword(kw) and (
                _peek_is_lparen(lex)
                or (kw == "range" and lex.pos < len(lex.s)
                    and lex.s[lex.pos] == "[")):
            return fn(lex, field_name)
    if lex.is_keyword(",", ")", "[", "]", "|") or lex.is_end():
        raise ParseError(f"unexpected token {lex.token!r} "
                         f"near ...{lex.context()}")
    if lex.is_keyword("and", "or"):
        # reserved keywords can't start a phrase (reference reservedKeywords
        # — parser.go:3101-3115); quote them to search literally
        raise ParseError(f"reserved keyword {lex.token!r} cannot be used "
                         f"as a search phrase; quote it to search literally")
    phrase = _get_compound_phrase(lex, allow_colon=bool(field_name))
    return _parse_filter_for_phrase(lex, phrase, field_name)


def _peek_is_lparen(lex: Lexer) -> bool:
    # function-style keywords must be followed immediately by '('
    return lex.pos < len(lex.s) and lex.s[lex.pos] == "("


_STOP_TOKENS = ("*", ",", "(", ")", "[", "]", "|", "")


def _get_compound_phrase(lex: Lexer, allow_colon: bool) -> str:
    stop = _STOP_TOKENS if allow_colon else _STOP_TOKENS + (":",)
    if lex.is_keyword(*stop):
        raise ParseError(f"compound phrase cannot start with {lex.token!r}")
    phrase = lex.token
    raw = lex.raw_token
    was_quoted = lex.is_quoted
    lex.next_token()
    suffix = ""
    while not lex.is_skipped_space and not lex.is_keyword(*stop) \
            and not lex.is_end():
        suffix += lex.raw_token if not lex.is_quoted else lex.token
        lex.next_token()
    if not suffix:
        return phrase
    if was_quoted:
        return phrase + suffix
    return raw + suffix


def _get_compound_token(lex: Lexer,
                        stop=(",", "(", ")", "[", "]", "|", "")) -> str:
    if lex.is_keyword(*stop):
        raise ParseError(f"compound token cannot start with {lex.token!r}")
    s = lex.token
    raw = lex.raw_token
    was_quoted = lex.is_quoted
    lex.next_token()
    suffix = ""
    while not lex.is_skipped_space and not lex.is_keyword(*stop) \
            and not lex.is_end():
        suffix += lex.raw_token if not lex.is_quoted else lex.token
        lex.next_token()
    if not suffix:
        return s
    return (s if was_quoted else raw) + suffix


def _parse_filter_for_phrase(lex: Lexer, phrase: str,
                             field_name: str) -> Filter:
    if field_name or not lex.is_keyword(":"):
        if lex.is_keyword("*") and not lex.is_skipped_space:
            lex.next_token()
            return FilterPrefix(field_name, phrase)
        return FilterPhrase(field_name, phrase)
    # phrase is actually a field name
    field_name = phrase
    lex.next_token()
    if field_name == "_time":
        return _parse_filter_time_generic(lex)
    if field_name == "_stream_id":
        return _parse_filter_stream_id(lex)
    if field_name == "_stream":
        return parse_generic_filter(lex, field_name)
    return parse_generic_filter(lex, field_name)


def _parse_parens(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    f = parse_filter_or(lex, field_name)
    if not lex.is_keyword(")"):
        raise ParseError(f"missing ')' ; got {lex.token!r}")
    lex.next_token()
    return f


# ---- function-style filters ----

def _parse_func_args(lex: Lexer) -> list[str]:
    """Parse `(arg, arg, ...)`; each arg is a compound token or quoted str."""
    if not lex.is_keyword("("):
        raise ParseError(f"missing '(' ; got {lex.token!r}")
    lex.next_token()
    args: list[str] = []
    while not lex.is_keyword(")"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        if lex.is_keyword("*") :
            args.append("*")
            lex.next_token()
            continue
        args.append(_get_compound_token(lex))
    lex.next_token()
    return args


def parse_query_in_parens(lex: Lexer) -> Query:
    """Parse `(full query)` — used by the join/union pipes."""
    if not lex.is_keyword("("):
        raise ParseError("missing '('")
    lex.next_token()
    q = _parse_query_internal(lex)
    if not lex.is_keyword(")"):
        raise ParseError("missing ')' after query")
    lex.next_token()
    return q


def _try_parse_subquery(lex: Lexer):
    """Detect `(subquery...)` for in()/contains_*: returns Query or None."""
    # a subquery starts with '(' and contains a full query; we detect it by
    # attempting a parse and falling back to plain args on failure
    save = (lex.pos, lex.token, lex.raw_token, lex.prev_token,
            lex.is_quoted, lex.is_skipped_space)
    try:
        if not lex.is_keyword("("):
            return None
        lex.next_token()
        q = _parse_query_internal(lex)
        if not lex.is_keyword(")"):
            raise ParseError("not a subquery")
        # heuristic: a subquery must contain a pipe with explicit fields
        # or a star filter is not enough to distinguish: require pipes
        if not q.pipes:
            raise ParseError("not a subquery")
        lex.next_token()
        return q
    except (ParseError, ValueError):
        (lex.pos, lex.token, lex.raw_token, lex.prev_token,
         lex.is_quoted, lex.is_skipped_space) = save
        return None


def _parse_in(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    sub = _try_parse_subquery(lex)
    if sub is not None:
        return FilterIn(field_name, [], subquery=sub)
    args = _parse_func_args(lex)
    if args == ["*"]:
        return FilterNoop()
    return FilterIn(field_name, args)


def _parse_contains_all(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    sub = _try_parse_subquery(lex)
    if sub is not None:
        return FilterContainsAll(field_name, [], subquery=sub)
    return FilterContainsAll(field_name, _parse_func_args(lex))


def _parse_contains_any(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    sub = _try_parse_subquery(lex)
    if sub is not None:
        return FilterContainsAny(field_name, [], subquery=sub)
    return FilterContainsAny(field_name, _parse_func_args(lex))


def _parse_exact(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args_raw_star(lex)
    if len(args) == 1 and args[0][1]:
        return FilterExactPrefix(field_name, args[0][0])
    if len(args) != 1:
        raise ParseError("exact() expects one arg")
    return FilterExact(field_name, args[0][0])


def _parse_func_args_raw_star(lex: Lexer) -> list[tuple[str, bool]]:
    """Args where a trailing `*` marks a prefix: exact(foo*)."""
    if not lex.is_keyword("("):
        raise ParseError("missing '('")
    lex.next_token()
    args: list[tuple[str, bool]] = []
    while not lex.is_keyword(")"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        tok = _get_compound_token(lex, stop=("*", ",", "(", ")", "|", ""))
        star = False
        if lex.is_keyword("*") and not lex.is_skipped_space:
            star = True
            lex.next_token()
        args.append((tok, star))
    lex.next_token()
    return args


def _parse_i(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args_raw_star(lex)
    if len(args) != 1:
        raise ParseError("i() expects one arg")
    phrase, star = args[0]
    if star:
        return FilterAnyCasePrefix(field_name, phrase)
    return FilterAnyCasePhrase(field_name, phrase)


def _parse_regexp_func(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) != 1:
        raise ParseError("re() expects one arg")
    return FilterRegexp(field_name, args[0])


def _parse_regexp_tail(lex: Lexer, field_name: str) -> Filter:
    if lex.is_quoted:
        pat = lex.token
        lex.next_token()
    else:
        pat = _get_compound_token(lex)
    return FilterRegexp(field_name, pat)


def _parse_eq(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    return _parse_eq_tail(lex, field_name)


def _parse_eq_tail(lex: Lexer, field_name: str) -> Filter:
    if lex.is_keyword("*") :
        lex.next_token()
        return FilterExactPrefix(field_name, "")
    value = _get_compound_token(lex, stop=("*", ",", "(", ")", "[", "]",
                                           "|", ""))
    if lex.is_keyword("*") and not lex.is_skipped_space:
        lex.next_token()
        return FilterExactPrefix(field_name, value)
    return FilterExact(field_name, value)


def _parse_gt(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    eq = False
    if lex.is_keyword("=") and not lex.is_skipped_space:
        eq = True
        lex.next_token()
    v = _get_compound_token(lex)
    fv = parse_number(v)
    if math.isnan(fv):
        raise ParseError(f"cannot parse number {v!r} after '>'")
    op = ">=" if eq else ">"
    minv = fv if eq else math.nextafter(fv, math.inf)
    return FilterRange(field_name, minv, math.inf, repr_str=f"{op}{v}")


def _parse_lt(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    eq = False
    if lex.is_keyword("=") and not lex.is_skipped_space:
        eq = True
        lex.next_token()
    v = _get_compound_token(lex)
    fv = parse_number(v)
    if math.isnan(fv):
        raise ParseError(f"cannot parse number {v!r} after '<'")
    op = "<=" if eq else "<"
    maxv = fv if eq else math.nextafter(fv, -math.inf)
    return FilterRange(field_name, -math.inf, maxv, repr_str=f"{op}{v}")


def _parse_range(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    if not lex.is_keyword("(", "["):
        raise ParseError("range must be followed by '(' or '['")
    inc_lo = lex.is_keyword("[")
    lex.next_token()
    lo_s = _get_compound_token(lex)
    if not lex.is_keyword(","):
        raise ParseError("missing ',' in range()")
    lex.next_token()
    hi_s = _get_compound_token(lex)
    if not lex.is_keyword(")", "]"):
        raise ParseError("missing ')' or ']' in range()")
    inc_hi = lex.is_keyword("]")
    lex.next_token()
    lo = parse_number(lo_s)
    hi = parse_number(hi_s)
    if math.isnan(lo) or math.isnan(hi):
        raise ParseError(f"cannot parse range bounds ({lo_s},{hi_s})")
    rs = f"range{'[' if inc_lo else '('}{lo_s},{hi_s}{']' if inc_hi else ')'}"
    if not inc_lo:
        lo = math.nextafter(lo, math.inf)
    if not inc_hi:
        hi = math.nextafter(hi, -math.inf)
    return FilterRange(field_name, lo, hi, repr_str=rs)


def _parse_ipv4_range(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) == 1:
        # CIDR form
        s = args[0]
        if "/" in s:
            base, bits = s.rsplit("/", 1)
            ip = parse_ipv4(base)
            if ip is None or not bits.isdigit() or int(bits) > 32:
                raise ParseError(f"invalid CIDR {s!r}")
            shift = 32 - int(bits)
            lo = (ip >> shift) << shift
            hi = lo | ((1 << shift) - 1)
        else:
            ip = parse_ipv4(s)
            if ip is None:
                raise ParseError(f"invalid IP {s!r}")
            lo = hi = ip
        return FilterIPv4Range(field_name, lo, hi)
    if len(args) != 2:
        raise ParseError("ipv4_range() expects 1 or 2 args")
    lo = parse_ipv4(args[0])
    hi = parse_ipv4(args[1])
    if lo is None or hi is None:
        raise ParseError(f"invalid IPs in ipv4_range{args}")
    return FilterIPv4Range(field_name, lo, hi)


def _parse_len_range(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) != 2:
        raise ParseError("len_range() expects 2 args")

    def _bound(s, dflt):
        if s.lower() == "inf":
            return dflt
        v = parse_number(s)
        if math.isnan(v):
            raise ParseError(f"bad len_range bound {s!r}")
        return int(v)
    return FilterLenRange(field_name, _bound(args[0], 0),
                          _bound(args[1], 1 << 62))


def _parse_string_range(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) != 2:
        raise ParseError("string_range() expects 2 args")
    return FilterStringRange(field_name, args[0], args[1])


def _parse_value_type(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) != 1:
        raise ParseError("value_type() expects 1 arg")
    return FilterValueType(field_name, args[0])


def _parse_eq_field(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    if len(args) != 1:
        raise ParseError("eq_field() expects 1 arg")
    return FilterEqField(field_name, args[0])


def _parse_le_field(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    return FilterLeField(field_name, args[0], strict=False)


def _parse_lt_field(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    args = _parse_func_args(lex)
    return FilterLeField(field_name, args[0], strict=True)


def _parse_seq(lex: Lexer, field_name: str) -> Filter:
    lex.next_token()
    return FilterSequence(field_name, _parse_func_args(lex))


_FUNC_FILTERS = {
    "contains_all": _parse_contains_all,
    "contains_any": _parse_contains_any,
    "eq_field": _parse_eq_field,
    "exact": _parse_exact,
    "i": _parse_i,
    "in": _parse_in,
    "ipv4_range": _parse_ipv4_range,
    "le_field": _parse_le_field,
    "len_range": _parse_len_range,
    "lt_field": _parse_lt_field,
    "range": _parse_range,
    "re": _parse_regexp_func,
    "seq": _parse_seq,
    "string_range": _parse_string_range,
    "value_type": _parse_value_type,
}


# ---- _time filters ----

def _now_ns(lex: Lexer) -> int:
    if lex.timestamp is not None:
        return lex.timestamp
    import time
    return time.time_ns()


def _parse_offset_modifier(lex: Lexer) -> int:
    if lex.is_keyword("offset"):
        lex.next_token()
        tok = _get_compound_token(lex)
        d = parse_duration(tok)
        if d is None:
            raise ParseError(f"invalid offset duration {tok!r}")
        return d
    return 0


def _parse_filter_time_generic(lex: Lexer) -> Filter:
    if lex.is_keyword("day_range"):
        return _parse_day_range(lex)
    if lex.is_keyword("week_range"):
        return _parse_week_range(lex)
    f = _parse_filter_time(lex)
    if lex.is_keyword("offset"):
        lex.next_token()
        tok = _get_compound_token(lex)
        off = parse_duration(tok)
        if off is None:
            raise ParseError(f"invalid offset duration {tok!r}")
        f = FilterTime(f.min_ts - off, f.max_ts - off,
                       repr_str=f"{f.repr_str} offset {tok}".strip())
    return f


def _parse_filter_time(lex: Lexer) -> FilterTime:
    if lex.is_keyword("[", "("):
        inc_lo = lex.is_keyword("[")
        lex.next_token()
        lo_s = _get_compound_token(lex)
        if not lex.is_keyword(","):
            raise ParseError("missing ',' in _time range")
        lex.next_token()
        hi_s = _get_compound_token(lex)
        if not lex.is_keyword("]", ")"):
            raise ParseError("missing ']' or ')' in _time range")
        inc_hi = lex.is_keyword("]")
        lex.next_token()
        lo = _time_bound(lex, lo_s, end=False)
        hi = _time_bound(lex, hi_s, end=True)
        if not inc_lo:
            lo += 1
        if not inc_hi:
            # exclusive end at the *start* of the named instant
            hi = _time_bound(lex, hi_s, end=False) - 1
        rs = f"{'[' if inc_lo else '('}{lo_s},{hi_s}{']' if inc_hi else ')'}"
        return FilterTime(lo, hi, repr_str=rs)
    if lex.is_keyword(">"):
        lex.next_token()
        eq = False
        if lex.is_keyword("=") and not lex.is_skipped_space:
            eq = True
            lex.next_token()
        tok = _get_compound_token(lex)
        t = _time_bound(lex, tok, end=True)
        if eq:
            t = _time_bound(lex, tok, end=False)
        op = ">=" if eq else ">"
        return FilterTime(t if eq else t + 1, MAX_TS, repr_str=f"{op}{tok}")
    if lex.is_keyword("<"):
        lex.next_token()
        eq = False
        if lex.is_keyword("=") and not lex.is_skipped_space:
            eq = True
            lex.next_token()
        tok = _get_compound_token(lex)
        t = _time_bound(lex, tok, end=eq)
        if not eq:
            t = _time_bound(lex, tok, end=False) - 1
        op = "<=" if eq else "<"
        return FilterTime(MIN_TS, t, repr_str=f"{op}{tok}")
    if lex.is_keyword("="):
        lex.next_token()
    tok = _get_compound_token(lex)
    d = parse_duration(tok)
    if d is not None:
        now = _now_ns(lex)
        return FilterTime(now - abs(d), now, repr_str=tok)
    tb = ts_bounds(tok)
    if tb is not None:
        return FilterTime(tb[0], tb[1], repr_str=tok)
    raise ParseError(f"cannot parse _time filter value {tok!r}")


def _time_bound(lex: Lexer, s: str, end: bool) -> int:
    if s == "now":
        return _now_ns(lex)
    d = parse_duration(s)
    if d is not None:
        return _now_ns(lex) + d if d < 0 else _now_ns(lex) - d
    tb = ts_bounds(s)
    if tb is None:
        if s.isdigit() or (s[:1] == "-" and s[1:].isdigit()):
            # bare integer: unix seconds/millis/micros/nanos by magnitude
            # — FilterTime.to_string() serializes raw nanos, and the
            # cluster frontend round-trips queries through to_string()
            from ..server.insertutil import parse_timestamp
            ts = parse_timestamp(int(s))
            if ts is not None:
                return ts
        raise ParseError(f"cannot parse time bound {s!r}")
    return tb[1] if end else tb[0]


def _parse_day_range(lex: Lexer) -> Filter:
    lex.next_token()
    if not lex.is_keyword("[", "("):
        raise ParseError("day_range must be followed by '[' or '('")
    inc_lo = lex.is_keyword("[")
    lex.next_token()
    lo_s = _get_compound_token(lex)
    if not lex.is_keyword(","):
        raise ParseError("missing ',' in day_range")
    lex.next_token()
    hi_s = _get_compound_token(lex)
    if not lex.is_keyword("]", ")"):
        raise ParseError("missing ']' or ')' in day_range")
    inc_hi = lex.is_keyword("]")
    lex.next_token()
    off = _parse_offset_modifier(lex)

    def _day_off(s):
        parts = s.split(":")
        if len(parts) != 2 or not parts[0].isdigit() or not parts[1].isdigit():
            raise ParseError(f"invalid day_range bound {s!r}; want hh:mm")
        return (int(parts[0]) * 3600 + int(parts[1]) * 60) * NS
    lo = _day_off(lo_s)
    hi = _day_off(hi_s)
    if not inc_lo:
        lo += 1
    if not inc_hi:
        hi -= 1
    rs = f"{'[' if inc_lo else '('}{lo_s},{hi_s}{']' if inc_hi else ')'}"
    return FilterDayRange(lo, hi, tz_offset_ns=-off, repr_str=rs)


_WEEKDAYS = {
    "sun": 0, "sunday": 0, "mon": 1, "monday": 1, "tue": 2, "tuesday": 2,
    "wed": 3, "wednesday": 3, "thu": 4, "thursday": 4, "fri": 5,
    "friday": 5, "sat": 6, "saturday": 6,
}


def _parse_week_range(lex: Lexer) -> Filter:
    lex.next_token()
    if not lex.is_keyword("[", "("):
        raise ParseError("week_range must be followed by '[' or '('")
    inc_lo = lex.is_keyword("[")
    lex.next_token()
    lo_s = _get_compound_token(lex)
    if not lex.is_keyword(","):
        raise ParseError("missing ',' in week_range")
    lex.next_token()
    hi_s = _get_compound_token(lex)
    if not lex.is_keyword("]", ")"):
        raise ParseError("missing ']' or ')' in week_range")
    inc_hi = lex.is_keyword("]")
    lex.next_token()
    off = _parse_offset_modifier(lex)
    try:
        lo = _WEEKDAYS[lo_s.lower()]
        hi = _WEEKDAYS[hi_s.lower()]
    except KeyError:
        raise ParseError(f"invalid week_range bounds [{lo_s},{hi_s}]")
    if not inc_lo:
        lo += 1
    if not inc_hi:
        hi -= 1
    rs = f"{'[' if inc_lo else '('}{lo_s},{hi_s}{']' if inc_hi else ')'}"
    return FilterWeekRange(lo, hi, tz_offset_ns=-off, repr_str=rs)


# ---- _stream / _stream_id ----

def _parse_filter_stream(lex: Lexer) -> Filter:
    """Parse `{tag op "value" [,...] [or ...]}`."""
    lex.next_token()
    or_groups: list[tuple[TagFilter, ...]] = []
    cur: list[TagFilter] = []
    while not lex.is_keyword("}"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        if lex.is_keyword("or"):
            if cur:
                or_groups.append(tuple(cur))
                cur = []
            lex.next_token()
            continue
        label = _get_compound_token(lex, stop=("=", "!=", "=~", "!~", "{",
                                               "}", ",", "(", ")", "|", ""))
        if lex.is_keyword("=", "!=", "=~", "!~"):
            op = lex.token
            lex.next_token()
        else:
            raise ParseError(f"missing stream filter op after {label!r}")
        if lex.is_keyword("in") and not lex.is_quoted:
            # label in (v1, v2) — only with '=' / '!='
            raise ParseError("label in(...) inside stream filter "
                             "not supported yet")
        value = lex.token
        lex.next_token()
        cur.append(TagFilter(label, op, value))
    lex.next_token()
    if cur:
        or_groups.append(tuple(cur))
    if not or_groups:
        return FilterNoop()
    return FilterStream(StreamFilter(tuple(or_groups)))


def _parse_filter_stream_id(lex: Lexer) -> Filter:
    if lex.is_keyword("in") and _peek_is_lparen(lex):
        lex.next_token()
        args = _parse_func_args(lex)
        return FilterStreamID(args)
    tok = _get_compound_token(lex)
    return FilterStreamID([tok])
