"""LogsQL lexer.

Token rules mirror the reference lexer (lib/logstorage/parser.go:150-245):
word tokens are maximal runs of token runes plus '.', strings quote with
double/back/single quotes (Go strconv unquoting rules), `=~` / `!=` / `!~`
are two-char tokens, `#` starts a line comment, and the lexer exposes
`prev_token` / `is_skipped_space` so the parser can reassemble compound
phrases like `foo-bar:baz` exactly the way the reference does.
"""

from __future__ import annotations


def _is_token_char(c: str) -> bool:
    return (c.isascii() and (c.isalnum() or c == "_")) or \
        (not c.isascii() and (c.isalpha() or c.isdigit() or c == "_"))


def unquote(raw: str) -> str:
    """Go-style strconv.Unquote for the three LogsQL quote kinds."""
    if len(raw) >= 2 and raw[0] == "`" and raw[-1] == "`":
        return raw[1:-1]
    if len(raw) < 2 or raw[0] not in "\"'" or raw[-1] != raw[0]:
        raise ValueError(f"invalid quoted string: {raw!r}")
    q = raw[0]
    s = raw[1:-1]
    out = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c != "\\":
            out.append(c)
            i += 1
            continue
        if i + 1 >= n:
            raise ValueError(f"trailing backslash in {raw!r}")
        e = s[i + 1]
        i += 2
        if e == "n":
            out.append("\n")
        elif e == "t":
            out.append("\t")
        elif e == "r":
            out.append("\r")
        elif e == "a":
            out.append("\a")
        elif e == "b":
            out.append("\b")
        elif e == "f":
            out.append("\f")
        elif e == "v":
            out.append("\v")
        elif e == "\\":
            out.append("\\")
        elif e == q:
            out.append(q)
        elif e in "\"'":
            out.append(e)
        elif e == "x":
            out.append(chr(int(s[i:i + 2], 16)))
            i += 2
        elif e == "u":
            out.append(chr(int(s[i:i + 4], 16)))
            i += 4
        elif e == "U":
            out.append(chr(int(s[i:i + 8], 16)))
            i += 8
        elif e in "01234567":
            out.append(chr(int(s[i - 1:i + 2], 8)))
            i += 2
        else:
            raise ValueError(f"unknown escape \\{e} in {raw!r}")
    return "".join(out)


def quote_token_if_needed(s: str) -> str:
    if s and all(_is_token_char(c) or c == "." for c in s):
        return s
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


class Lexer:
    def __init__(self, s: str, timestamp: int | None = None):
        self.s = s
        self.pos = 0
        self.token = ""
        self.raw_token = ""
        self.prev_token = ""
        self.is_skipped_space = False
        self.is_quoted = False
        self.timestamp = timestamp
        self.next_token()

    def is_end(self) -> bool:
        return self.token == "" and not self.is_quoted and \
            self.pos >= len(self.s)

    def is_keyword(self, *kws: str) -> bool:
        if self.is_quoted:
            return False
        t = self.token.lower()
        return any(t == k for k in kws)

    def is_prev_token(self, *kws: str) -> bool:
        return self.prev_token.lower() in kws

    def context(self) -> str:
        return self.s[max(0, self.pos - 30):self.pos]

    def next_token(self) -> None:
        s, i, n = self.s, self.pos, len(self.s)
        self.prev_token = self.token
        self.token = ""
        self.raw_token = ""
        self.is_quoted = False
        self.is_skipped_space = False

        while True:
            # skip whitespace
            while i < n and s[i].isspace():
                self.is_skipped_space = True
                i += 1
            # skip comments
            if i < n and s[i] == "#":
                nl = s.find("\n", i)
                i = n if nl < 0 else nl + 1
                continue
            break
        if i >= n:
            self.pos = i
            return

        start = i
        c = s[i]
        # word token: token runes plus '.'
        if _is_token_char(c) or c == ".":
            while i < n and (_is_token_char(s[i]) or s[i] == "."):
                i += 1
            self.token = s[start:i]
            self.raw_token = self.token
            self.pos = i
            return

        if c in "\"'`":
            j = i + 1
            while j < n:
                if s[j] == "\\" and c != "`" and j + 1 < n:
                    j += 2
                    continue
                if s[j] == c:
                    break
                j += 1
            if j >= n:
                raise ValueError(
                    f"missing closing quote for [{s[i:]}]")
            raw = s[i:j + 1]
            self.token = unquote(raw)
            self.raw_token = raw
            self.is_quoted = True
            self.pos = j + 1
            return

        if c == "=" and i + 1 < n and s[i + 1] == "~":
            self.token = self.raw_token = "=~"
            self.pos = i + 2
            return
        if c == "!" and i + 1 < n and s[i + 1] in "~=":
            self.token = self.raw_token = s[i:i + 2]
            self.pos = i + 2
            return

        self.token = self.raw_token = c
        self.pos = i + 1


def is_token_like(s: str) -> bool:
    return bool(s) and all(_is_token_char(c) or c == "." for c in s)
