"""Scalar value-match semantics for LogsQL filters.

These definitions are the *correctness oracle* shared by the CPU executor and
the TPU kernels: every kernel must produce bit-identical results to these
functions.  Semantics mirror the reference:

- match_phrase: substring occurrence with word-boundary checks on both sides
  (filter_phrase.go:211-268)
- match_prefix: occurrence with a word boundary before it only
  (filter_prefix.go:318-352); empty prefix matches any non-empty string
- match_exact_prefix: plain startswith (filter_exact_prefix.go:275)
- match_sequence: ordered non-overlapping phrase occurrences
  (filter_sequence.go:260)
- word-char definition: ASCII [A-Za-z0-9_] plus all non-ASCII characters
  (departure: the reference uses unicode letter/digit classes —
  tokenizer.go:142-148; treating all non-ASCII as word chars keeps the byte-
  level arena tokenizer, this module and the device kernels exactly agreed)
"""

from __future__ import annotations

import math
import re


def is_word_char(c: str) -> bool:
    return c.isascii() and (c.isalnum() or c == "_") or not c.isascii()


def match_phrase(s: str, phrase: str) -> bool:
    if not phrase:
        return not s
    starts_tok = is_word_char(phrase[0])
    ends_tok = is_word_char(phrase[-1])
    pos = 0
    while True:
        n = s.find(phrase, pos)
        if n < 0:
            return False
        if starts_tok and n > 0 and is_word_char(s[n - 1]):
            pos = n + 1
            continue
        end = n + len(phrase)
        if ends_tok and end < len(s) and is_word_char(s[end]):
            pos = n + 1
            continue
        return True


def match_prefix(s: str, prefix: str) -> bool:
    if not prefix:
        return len(s) > 0
    starts_tok = is_word_char(prefix[0])
    pos = 0
    while True:
        n = s.find(prefix, pos)
        if n < 0:
            return False
        if starts_tok and n > 0 and is_word_char(s[n - 1]):
            pos = n + 1
            continue
        return True


def match_exact_prefix(s: str, prefix: str) -> bool:
    return s.startswith(prefix)


def match_any_case_phrase(s: str, phrase_lower: str) -> bool:
    return match_phrase(s.lower(), phrase_lower)


def match_any_case_prefix(s: str, prefix_lower: str) -> bool:
    return match_prefix(s.lower(), prefix_lower)


def phrase_pos(s: str, phrase: str) -> int:
    """First word-boundary occurrence of phrase in s; -1 if none
    (reference getPhrasePos — filter_phrase.go:219-268)."""
    if not phrase:
        return 0
    starts_tok = is_word_char(phrase[0])
    ends_tok = is_word_char(phrase[-1])
    pos = 0
    while True:
        n = s.find(phrase, pos)
        if n < 0:
            return -1
        if starts_tok and n > 0 and is_word_char(s[n - 1]):
            pos = n + 1
            continue
        end = n + len(phrase)
        if ends_tok and end < len(s) and is_word_char(s[end]):
            pos = n + 1
            continue
        return n


def match_sequence(s: str, phrases: list[str]) -> bool:
    """Ordered phrase occurrences, each at word boundaries
    (reference matchSequence — filter_sequence.go:260)."""
    for p in phrases:
        n = phrase_pos(s, p)
        if n < 0:
            return False
        s = s[n + len(p):]
    return True


def match_string_range(s: str, min_value: str, max_value: str) -> bool:
    return min_value <= s < max_value


def match_len_range(s: str, min_len: int, max_len: int) -> bool:
    # length is measured in unicode code points (reference measures runes —
    # filter_len_range.go uses utf8.RuneCountInString)
    return min_len <= len(s) <= max_len


_FLOAT_RE = re.compile(r"^[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?$")
_SUFFIXES = {
    "k": 1e3, "m": 1e6, "g": 1e9, "t": 1e12,
    "ki": 1024.0, "mi": 1024.0 ** 2, "gi": 1024.0 ** 3, "ti": 1024.0 ** 4,
    "kb": 1e3, "mb": 1e6, "gb": 1e9, "tb": 1e12,
    "kib": 1024.0, "mib": 1024.0 ** 2, "gib": 1024.0 ** 3, "tib": 1024.0 ** 4,
    "b": 1.0,
}


def parse_number(s: str) -> float:
    """Parse a LogsQL number, with size suffixes (10KB, 5MiB) and inf/nan."""
    if not s:
        return math.nan
    t = s.strip().lower().replace("_", "")
    if t in ("inf", "+inf"):
        return math.inf
    if t == "-inf":
        return -math.inf
    if t == "nan":
        return math.nan
    mult = 1.0
    for suf in ("kib", "mib", "gib", "tib", "kb", "mb", "gb", "tb",
                "ki", "mi", "gi", "ti", "k", "m", "g", "t", "b"):
        if t.endswith(suf):
            base = t[: -len(suf)]
            if base and _FLOAT_RE.match(base):
                t = base
                mult = _SUFFIXES[suf]
            break
    try:
        return float(t) * mult
    except ValueError:
        return math.nan


def match_range(s: str, min_value: float, max_value: float) -> bool:
    v = parse_number(s)
    if math.isnan(v):
        return False
    return min_value <= v <= max_value


def parse_ipv4(s: str) -> int | None:
    parts = s.split(".")
    if len(parts) != 4:
        return None
    v = 0
    for p in parts:
        if not p.isdigit() or len(p) > 3:
            return None
        n = int(p)
        if n > 255:
            return None
        v = (v << 8) | n
    return v


def match_ipv4_range(s: str, min_value: int, max_value: int) -> bool:
    v = parse_ipv4(s)
    return v is not None and min_value <= v <= max_value


_VALUE_TYPE_RES = {
    # maps value_type() names to a string-level check for re-filter use
}


def match_value_type(type_name: str, want: str) -> bool:
    return type_name == want
