"""Aux pipes: join/union/stream_context (storage-backed) plus
collapse_nums/decolorize/hash/json_array_len/block_stats.

These complete the reference pipe registry (lib/logstorage/pipe.go:119-386).
join/union/stream_context take a storage handle via init_with_storage()
(engine.searcher.run_query installs it before building processors — the
analogue of the reference's initFilterInValues / withRunQuery hooks,
pipe_join.go, pipe_union.go, pipe_stream_context.go)."""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..engine.block_result import BlockResult, format_rfc3339, parse_rfc3339
from .duration import parse_duration
from .lexer import Lexer, quote_token_if_needed
from .pipes import (ParseError, Pipe, Processor, _parse_field_name,
                    _parse_uint, register_pipe)
from . import pipes_transform as _pt


def _if_mask(iff, br):
    return _pt._if_mask(iff, br)


def _if_str(iff):
    return _pt._if_str(iff)


def _maybe_if(lex):
    return _pt._maybe_if(lex)


def _parse_paren_fields(lex):
    return _pt._parse_paren_fields(lex)

NS = 1_000_000_000


class _StorageBackedPipe(Pipe):
    """Base for pipes that must run additional queries against storage."""

    def __init__(self):
        self._storage = None
        self._tenants = None
        self._runner = None

    def init_with_storage(self, storage, tenants, runner) -> None:
        self._storage = storage
        self._tenants = list(tenants)
        self._runner = runner

    def _collect_columns(self, q):
        """(cols, nrows) — the columnar collect contract
        (engine.searcher.run_query_collect_columns): bulk column
        lists, no per-row dicts, shared by local and cluster paths."""
        from ..engine.searcher import run_query_collect_columns
        if self._storage is None:
            raise ParseError(
                f"{self.name} requires storage-backed execution")
        return run_query_collect_columns(self._storage, self._tenants,
                                         q, runner=self._runner)


# ---------------- join ----------------

@dataclass(repr=False)
class PipeJoin(_StorageBackedPipe):
    by: list = dc_field(default_factory=list)
    query: object = None          # parsed Query
    inner: bool = False
    prefix: str = ""

    name = "join"

    def __post_init__(self):
        _StorageBackedPipe.__init__(self)

    def to_string(self):
        s = (f"join by ({', '.join(self.by)}) "
             f"({self.query.to_string()})")
        if self.inner:
            s += " inner"
        if self.prefix:
            s += " prefix " + quote_token_if_needed(self.prefix)
        return s

    def needed_fields(self):
        return set(self.by)

    def make_processor(self, next_p):
        pipe = self
        # hash-join map built from the subquery once (reference builds it in
        # storage_search.go:212-272); the subquery result arrives as
        # bulk columns and only the per-GROUP extras become dicts
        cols, nr = pipe._collect_columns(pipe.query)
        by = pipe.by
        by_cols = [cols.get(f) or [""] * nr for f in by]
        extra_items = [(pipe.prefix + k, v) for k, v in cols.items()
                       if k not in by]
        jmap: dict[tuple, list[dict]] = {}
        for i in range(nr):
            key = tuple(bc[i] for bc in by_cols)
            extra = {k: vc[i] for k, vc in extra_items
                     if vc[i] != ""}
            jmap.setdefault(key, []).append(extra)

        class P(Processor):
            def write_block(self, br):
                names = br.column_names()
                cols = {n: br.column(n) for n in names}
                out_rows: list[dict] = []
                for i in range(br.nrows):
                    key = tuple(cols.get(f, [""] * br.nrows)[i] for f in by)
                    base = {n: cols[n][i] for n in names}
                    matches = jmap.get(key)
                    if not matches:
                        if not pipe.inner:
                            out_rows.append(base)
                        continue
                    for m in matches:
                        out_rows.append({**base, **m})
                if out_rows:
                    all_names: dict[str, None] = {}
                    for r in out_rows:
                        for k in r:
                            all_names.setdefault(k, None)
                    out_cols = {n: [r.get(n, "") for r in out_rows]
                                for n in all_names}
                    self.next_p.write_block(
                        BlockResult.from_columns(out_cols))
        return P(next_p)


# ---------------- union ----------------

@dataclass(repr=False)
class PipeUnion(_StorageBackedPipe):
    query: object = None

    name = "union"

    def __post_init__(self):
        _StorageBackedPipe.__init__(self)

    def to_string(self):
        return f"union ({self.query.to_string()})"

    def input_fields(self, out_needed):
        return out_needed

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                self.next_p.write_block(br)

            def flush(self):
                # the union'd query runs after the main one finishes
                # (reference pipe_union.go); its columns pass straight
                # through — no row-dict round trip
                cols, nr = pipe._collect_columns(pipe.query)
                if nr and cols:
                    self.next_p.write_block(BlockResult.from_columns(cols))
                self.next_p.flush()
        return P(next_p)


# ---------------- stream_context ----------------

@dataclass(repr=False)
class PipeStreamContext(_StorageBackedPipe):
    before: int = 0
    after: int = 0
    time_window_ns: int = 3600 * NS

    name = "stream_context"

    def __post_init__(self):
        _StorageBackedPipe.__init__(self)

    def to_string(self):
        s = "stream_context"
        if self.before > 0:
            s += f" before {self.before}"
        if self.after > 0:
            s += f" after {self.after}"
        if self.before <= 0 and self.after <= 0:
            s += " after 0"
        if self.time_window_ns != 3600 * NS:
            s += f" time_window {self.time_window_ns // NS}s"
        return s

    def input_fields(self, out_needed):
        return {"*"}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                # stream_id -> sorted set of matched timestamps
                self.matched: dict[str, set] = {}

            def write_block(self, br):
                sids = br.column("_stream_id")
                ts = br.timestamps or [None] * br.nrows
                for i in range(br.nrows):
                    t = ts[i]
                    if t is None:
                        t = parse_rfc3339(br.column("_time")[i])
                    if t is not None:
                        self.matched.setdefault(sids[i], set()).add(t)

            def flush(self):
                w = pipe.time_window_ns
                for sid, tset in self.matched.items():
                    times = sorted(tset)
                    lo = format_rfc3339(times[0] - w)
                    hi = format_rfc3339(times[-1] + w)
                    qs = (f"_stream_id:{sid} "
                          f"_time:[{lo}, {hi}] | sort by (_time)")
                    cols, nr = pipe._collect_columns(qs)
                    keep_idx: set[int] = set()
                    row_ts = [parse_rfc3339(v) or 0
                              for v in cols.get("_time") or [""] * nr]
                    import bisect
                    for t in times:
                        # locate matched rows by bisect (row_ts is sorted)
                        # and take the surrounding window
                        # (reference pipe_stream_context.go)
                        i = bisect.bisect_left(row_ts, t)
                        while i < len(row_ts) and row_ts[i] == t:
                            a = max(0, i - pipe.before)
                            b = min(nr, i + pipe.after + 1)
                            keep_idx.update(range(a, b))
                            i += 1
                    keep = sorted(keep_idx)
                    if not keep:
                        continue
                    out_cols = {n: [vals[i] for i in keep]
                                for n, vals in cols.items()}
                    self.next_p.write_block(
                        BlockResult.from_columns(out_cols))
                self.next_p.flush()
        return P(next_p)


# ---------------- collapse_nums ----------------

_HEX_CHARS = set("0123456789abcdefABCDEF")
_SPECIAL_START = set("TXxvshm")
_SPECIAL_END = set("TZsmhunμ")


def _is_token_char(c: str) -> bool:
    return c.isalnum() or c == "_"


def _can_be_num(s: str) -> bool:
    if all(ch.isdigit() for ch in s):
        return True
    # hex runs: require >=4 chars and an even count ("be", "abc" stay text)
    return len(s) >= 4 and len(s) % 2 == 0


def collapse_nums(s: str) -> str:
    out = []
    start = 0
    num_start = -1
    for i, c in enumerate(s):
        if c in _HEX_CHARS:
            if num_start < 0 and (i == 0 or s[i - 1] in _SPECIAL_START or
                                  not _is_token_char(s[i - 1])):
                num_start = i
            continue
        if num_start < 0:
            continue
        out.append(s[start:num_start])
        if (c not in _SPECIAL_END and _is_token_char(c)) or \
                not _can_be_num(s[num_start:i]):
            out.append(s[num_start:i])
        else:
            out.append("<N>")
        start = i
        num_start = -1
    if num_start >= 0 and _can_be_num(s[num_start:]):
        out.append(s[start:num_start])
        out.append("<N>")
    else:
        out.append(s[start:])
    return "".join(out)


def _replace_skip_tail(s: str, old: str, new: str, skip_tail=None) -> str:
    out = []
    while True:
        n = s.find(old)
        if n < 0:
            out.append(s)
            return "".join(out)
        out.append(s[:n])
        out.append(new)
        s = s[n + len(old):]
        if skip_tail is not None:
            s = skip_tail(s)


def _skip_subsecs(s: str) -> str:
    if s.startswith(".<N>") or s.startswith(",<N>"):
        return s[4:]
    return s


def _skip_tz(s: str) -> str:
    if s.startswith("Z"):
        return s[1:]
    if s.startswith("-<N>:<N>") or s.startswith("+<N>:<N>"):
        return s[8:]
    return s


def prettify_collapsed(s: str) -> str:
    s = _replace_skip_tail(s, "<N>-<N>-<N>-<N>-<N>", "<UUID>")
    s = _replace_skip_tail(s, "<N>.<N>.<N>.<N>", "<IP4>")
    s = _replace_skip_tail(s, "<N>:<N>:<N>", "<TIME>", _skip_subsecs)
    s = _replace_skip_tail(s, "<N>-<N>-<N>", "<DATE>")
    s = _replace_skip_tail(s, "<N>/<N>/<N>", "<DATE>")
    s = _replace_skip_tail(s, "<DATE>T<TIME>", "<DATETIME>", _skip_tz)
    s = _replace_skip_tail(s, "<DATE> <TIME>", "<DATETIME>", _skip_tz)
    return s


@dataclass(repr=False)
class PipeCollapseNums(Pipe):
    field: str = "_msg"
    prettify: bool = False
    iff: object = None

    name = "collapse_nums"

    def to_string(self):
        s = "collapse_nums" + _if_str(self.iff)
        if self.field != "_msg":
            s += " at " + quote_token_if_needed(self.field)
        if self.prettify:
            s += " prettify"
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        out = {self.field}
        if self.iff is not None:
            out |= self.iff.needed_fields()
        return out

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                mask = _if_mask(pipe.iff, br)
                vals = br.column(pipe.field)
                out_vals = []
                for i, v in enumerate(vals):
                    if mask is not None and not mask[i]:
                        out_vals.append(v)
                        continue
                    c = collapse_nums(v)
                    if pipe.prettify:
                        c = prettify_collapsed(c)
                    out_vals.append(c)
                out = br.materialize()
                out._cols[pipe.field] = out_vals
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- decolorize ----------------

_ANSI_RE = re.compile(r"\x1b\[[0-9;]*m")


@dataclass(repr=False)
class PipeDecolorize(Pipe):
    field: str = "_msg"

    name = "decolorize"

    def to_string(self):
        s = "decolorize"
        if self.field != "_msg":
            s += " at " + quote_token_if_needed(self.field)
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return {self.field}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                vals = br.column(pipe.field)
                out = br.materialize()
                out._cols[pipe.field] = [_ANSI_RE.sub("", v) for v in vals]
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- hash / json_array_len / block_stats ----------------

@dataclass(repr=False)
class PipeHash(Pipe):
    field: str = "_msg"
    result_field: str = "_msg"

    name = "hash"

    def to_string(self):
        s = f"hash({quote_token_if_needed(self.field)})"
        if self.result_field != "_msg":
            s += " as " + quote_token_if_needed(self.result_field)
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return {self.field}

    def make_processor(self, next_p):
        from ..utils.hashing import xxh64
        pipe = self

        class P(Processor):
            def write_block(self, br):
                vals = br.column(pipe.field)
                out = br.materialize()
                out._cols[pipe.result_field] = [
                    str(xxh64(v.encode("utf-8"))) for v in vals]
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeJSONArrayLen(Pipe):
    field: str = "_msg"
    result_field: str = "_msg"

    name = "json_array_len"

    def to_string(self):
        s = f"json_array_len({quote_token_if_needed(self.field)})"
        if self.result_field != "_msg":
            s += " as " + quote_token_if_needed(self.result_field)
        return s

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return {self.field}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def write_block(self, br):
                vals = br.column(pipe.field)
                out_vals = []
                for v in vals:
                    try:
                        arr = json.loads(v)
                        out_vals.append(str(len(arr))
                                        if isinstance(arr, list) else "0")
                    except (ValueError, RecursionError):
                        out_vals.append("0")
                out = br.materialize()
                out._cols[pipe.result_field] = out_vals
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeBlockStats(Pipe):
    """Per-block per-column stats rows (reference pipe_block_stats.go:
    field/type/rows columns for storage debugging)."""

    name = "block_stats"

    def to_string(self):
        return "block_stats"

    def input_fields(self, out_needed):
        return {"*"}

    def make_processor(self, next_p):
        class P(Processor):
            def write_block(self, br):
                # fields-restricted views report like materialized blocks
                bs = br._bs if br._restrict is None else None
                rows_out = []
                if bs is not None:
                    part = bs.part
                    for name in bs.column_names():
                        meta = bs.column_meta(name)
                        from ..storage.values_encoder import VT_NAMES
                        vtype = "const" if meta is None else \
                            VT_NAMES[meta["t"]]
                        rows_out.append({
                            "field": name, "type": vtype,
                            "rows": str(bs.nrows),
                            "part_path": str(getattr(part, "path", "")
                                             or "inmemory")})
                else:
                    for name in br.column_names():
                        rows_out.append({"field": name, "type": "values",
                                         "rows": str(br.nrows),
                                         "part_path": ""})
                if rows_out:
                    names = ["field", "type", "rows", "part_path"]
                    cols = {n: [r[n] for r in rows_out] for n in names}
                    self.next_p.write_block(BlockResult.from_columns(cols))
        return P(next_p)


# ---------------- parsers + registration ----------------

def _parse_join(lex: Lexer):
    from .parser import parse_query_in_parens
    if lex.is_keyword("by"):
        lex.next_token()
    by = _parse_paren_fields(lex)
    if not lex.is_keyword("("):
        raise ParseError("missing '(' with join query")
    q = parse_query_in_parens(lex)
    p = PipeJoin(by, q)
    if lex.is_keyword("inner"):
        p.inner = True
        lex.next_token()
    if lex.is_keyword("prefix"):
        lex.next_token()
        p.prefix = _parse_field_name(lex)
    return p


def _parse_union(lex: Lexer):
    from .parser import parse_query_in_parens
    if not lex.is_keyword("("):
        raise ParseError("missing '(' with union query")
    return PipeUnion(parse_query_in_parens(lex))


def _parse_stream_context(lex: Lexer):
    p = PipeStreamContext()
    while True:
        if lex.is_keyword("before"):
            lex.next_token()
            p.before = _parse_uint(lex, "before")
        elif lex.is_keyword("after"):
            lex.next_token()
            p.after = _parse_uint(lex, "after")
        elif lex.is_keyword("time_window"):
            lex.next_token()
            d = parse_duration(lex.token)
            if d is None or d <= 0:
                raise ParseError(f"bad time_window {lex.token!r}")
            p.time_window_ns = d
            lex.next_token()
        else:
            break
    return p


def _parse_collapse_nums(lex: Lexer):
    iff = _maybe_if(lex)
    p = PipeCollapseNums(iff=iff)
    if lex.is_keyword("at"):
        lex.next_token()
        p.field = _parse_field_name(lex)
    if lex.is_keyword("prettify"):
        p.prettify = True
        lex.next_token()
    return p


def _parse_decolorize(lex: Lexer):
    p = PipeDecolorize()
    if lex.is_keyword("at"):
        lex.next_token()
        p.field = _parse_field_name(lex)
    return p


def _parse_fn_as(lex: Lexer, cls, what: str):
    if not lex.is_keyword("("):
        raise ParseError(f"missing '(' after {what}")
    lex.next_token()
    fld = _parse_field_name(lex)
    if not lex.is_keyword(")"):
        raise ParseError(f"missing ')' after {what} field")
    lex.next_token()
    p = cls(fld)
    if lex.is_keyword("as"):
        lex.next_token()
        p.result_field = _parse_field_name(lex)
    elif not lex.is_end() and not lex.is_keyword("|"):
        p.result_field = _parse_field_name(lex)
    return p


register_pipe("join", _parse_join)
register_pipe("union", _parse_union)
register_pipe("stream_context", _parse_stream_context)
register_pipe("collapse_nums", _parse_collapse_nums)
register_pipe("decolorize", _parse_decolorize)
register_pipe("hash", lambda lex: _parse_fn_as(lex, PipeHash, "hash"))
register_pipe("json_array_len",
              lambda lex: _parse_fn_as(lex, PipeJSONArrayLen,
                                       "json_array_len"))
register_pipe("block_stats", lambda lex: PipeBlockStats())
