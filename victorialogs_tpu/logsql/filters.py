"""LogsQL filter tree: AST nodes + CPU block evaluation.

The 26 filter kinds of the reference (lib/logstorage/filter_*.go; interface
filter.go:8-20).  Each node implements:

  apply_to_block(bs, bm)  — AND itself into a numpy bool bitmap over one
                            storage block (reference applyToBlockSearch)
  apply_to_values(vals_fn, n) -> mask — re-filtering over in-pipeline rows
                            (reference applyToBlockResult), used by `filter` pipe
  needed_fields()         — referenced field names for column pushdown
  to_string()             — canonical LogsQL rendering

Bloom-assisted pruning: phrase/prefix/exact/sequence/contains filters probe
the per-column token bloom before touching values (reference
matchBloomFilterAllTokens — filter_phrase.go:302) — on TPU this same probe is
the cheap block kill-path.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..storage.bloom import bloom_contains_all
from ..storage.values_encoder import (VT_FLOAT64, VT_INT64, VT_IPV4,
                                      VT_TIMESTAMP_ISO8601, VT_UINT8,
                                      VT_UINT16, VT_UINT32, VT_UINT64,
                                      VT_NAMES, VT_STRING, VT_DICT)
from ..utils.hashing import cached_token_hashes
from ..utils.tokenizer import tokenize_string
from ..engine.block_search import BlockSearch, visit_values
from .matchers import (is_word_char, match_any_case_phrase,
                       match_any_case_prefix, match_exact_prefix,
                       match_ipv4_range, match_len_range, match_phrase,
                       match_prefix, match_range, match_sequence,
                       match_string_range, parse_ipv4, parse_number)

_NUMERIC_VTS = (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64, VT_INT64,
                VT_FLOAT64)


def quote_str(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _q(field: str) -> str:
    return f"{field}:" if field else ""


class Filter:
    def apply_to_block(self, bs: BlockSearch, bm: np.ndarray) -> None:
        raise NotImplementedError

    def apply_to_values(self, get_values, nrows: int) -> np.ndarray:
        """Evaluate over arbitrary row values: get_values(field)->list[str]."""
        raise NotImplementedError

    def needed_fields(self) -> set:
        return set()

    def to_string(self) -> str:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.to_string()}>"


def _bloom_prunes(bs: BlockSearch, fld: str, f) -> bool:
    """True if the column bloom proves no row can match (all tokens of
    filter `f` are required); token hashes memoized on the filter so a
    query hashes them once, not once per block."""
    tokens = f._tokens()
    if not tokens:
        return False
    words = bs.bloom(fld)
    if words is None or words.shape[0] == 0:
        return False
    return not bloom_contains_all(words, cached_token_hashes(f, tokens))


def canonical_field(field: str) -> str:
    """Empty field name targets the message column (reference
    getCanonicalColumnName — a bare `foo` searches `_msg`)."""
    return field or "_msg"


def iter_and_path_token_leaves(f):
    """Yield (field, tokens, leaf) for bloom-prunable leaves on the
    top-level AND path.

    These leaves match nothing anywhere their required word tokens are
    absent, so a part whose aggregate filter (storage/filterbank.py)
    proves a token absent from EVERY block can be skipped outright —
    the per-block kill-path would have zeroed each block one by one.
    Only FilterAnd is recursed: under OR/NOT a leaf's emptiness doesn't
    imply the tree's.
    """
    if isinstance(f, FilterAnd):
        for sub in f.filters:
            yield from iter_and_path_token_leaves(sub)
    elif isinstance(f, _ValuePredFilter):
        toks = f._tokens()
        if toks:
            yield canonical_field(f.field), toks, f


def filter_plan_tree(f) -> dict:
    """Compact JSON-ready view of a filter tree for the EXPLAIN plan
    (obs/explain.py): operator kind, target field, and — on
    bloom-prunable leaves — the required word tokens the part-aggregate
    kill path (storage/filterbank.part_aggregate_prunes) can cite when
    it kills a part.  Purely descriptive: no evaluation, no token
    hashing."""
    kind = type(f).__name__.removeprefix("Filter").lower() or "filter"
    if isinstance(f, (FilterAnd, FilterOr)):
        return {"op": kind,
                "children": [filter_plan_tree(s) for s in f.filters]}
    if isinstance(f, FilterNot):
        return {"op": "not", "children": [filter_plan_tree(f.inner)]}
    node: dict = {"op": kind, "filter": f.to_string()}
    if isinstance(f, FilterTime):
        node["min_ts"] = f.min_ts
        node["max_ts"] = f.max_ts
        return node
    fld = getattr(f, "field", None)
    if fld is not None:
        node["field"] = canonical_field(fld)
    if isinstance(f, _ValuePredFilter):
        toks = f._tokens()
        if toks:
            # the tokens whose provable absence kills blocks (bloom
            # plane) and whole parts (Bloofi-style aggregate)
            node["prune_tokens"] = list(toks)
    return node


def _native_scan_ops(col, ops, combine: str):
    """AND/OR native scans over one column; None if any scan unavailable
    (caller falls back to the per-row Python path)."""
    from .. import native
    acc = None
    for op in ops:
        nb = native.phrase_scan_native(col.arena, col.offsets,
                                       col.lengths, *op)
        if nb is None:
            return None
        if acc is None:
            acc = nb
        elif combine == "and":
            acc &= nb
        else:
            acc |= nb
        if combine == "and" and not acc.any():
            break
    return acc


def _any_case_scan(col, phrase_lower: str, mode: int, st: bool,
                   et: bool, pred, bm) -> bool:
    """Case-insensitive native scan: ASCII-lower a copy of the arena and
    scan it; rows containing non-ASCII bytes verify through pred (their
    unicode case folding can differ, e.g. 'İ').lower()).  Returns False
    to fall back entirely."""
    if not phrase_lower.isascii() or not phrase_lower:
        return False
    from .. import native
    arena = col.arena
    low = arena.copy()
    up = (low >= 65) & (low <= 90)
    low[up] += 32
    nb = native.phrase_scan_native(low, col.offsets, col.lengths,
                                   phrase_lower.encode(), mode, st, et)
    if nb is None:
        return False
    highs = np.zeros(arena.shape[0] + 1, dtype=np.int64)
    np.cumsum(arena >= 128, out=highs[1:])
    offs = col.offsets
    rowhigh = (highs[offs + col.lengths] - highs[offs]) > 0
    bm &= nb | rowhigh
    check = bm & rowhigh
    if check.any():
        _native_verify(col, check, pred)
        bm &= ~rowhigh | check
    return True


def _native_verify(col, bm, pred) -> None:
    """pred() survivors of a native prefilter, decoded row-by-row."""
    arena, offs, lens = col.arena, col.offsets, col.lengths
    for i in np.nonzero(bm)[0]:
        o = int(offs[i])
        v = arena[o:o + int(lens[i])].tobytes().decode("utf-8", "replace")
        if not pred(v):
            bm[i] = False


class _ValuePredFilter(Filter):
    """Base for single-field filters evaluated as a per-value predicate."""

    field: str

    def _pred(self, v: str) -> bool:
        raise NotImplementedError

    def _tokens(self) -> list[str]:
        return []

    def _scan_spec(self) -> tuple | None:
        """(pattern_bytes, mode, starts_tok, ends_tok) for the native
        arena scan, or None to stay on the per-row Python path.  Modes
        mirror tpu/kernels.py; the Python matchers remain the oracle
        (randomized parity in tests/test_native.py)."""
        return None

    def _multi_scan_spec(self) -> tuple | None:
        """(ops, combine, verify) for multi-pattern native scans:
        ops = [(pattern_bytes, mode, starts_tok, ends_tok)], combine in
        {'and','or'}, verify => re-check survivors with _pred (mirrors
        the device leaf plans in tpu/batch.py)."""
        return None

    @staticmethod
    def _scan_column(bs: BlockSearch, fld: str):
        """The VT_STRING column eligible for a native arena scan, or None
        (special fields, consts, dict/numeric encodings stay on the
        per-value Python path that visit_values optimizes already)."""
        if fld in ("_time", "_stream", "_stream_id") or \
                fld in bs.consts():
            return None
        col = bs.column(fld)
        if col is None or col.vtype != VT_STRING:
            return None
        return col

    def apply_to_block(self, bs: BlockSearch, bm: np.ndarray) -> None:
        fld = canonical_field(self.field)
        if _bloom_prunes(bs, fld, self):
            bm[:] = False
            return
        # native arena scan: one memmem pass over a packed string column
        # instead of nrows Python predicate calls (host analogue of the
        # device kernel; ~20-50x on phrase/prefix/exact filters)
        spec = self._scan_spec()
        multi = None if spec is not None else self._multi_scan_spec()
        if spec is not None or multi is not None:
            col = self._scan_column(bs, fld)
            if col is not None:
                from .. import native
                if spec is not None:
                    nb = native.phrase_scan_native(
                        col.arena, col.offsets, col.lengths, *spec)
                    if nb is not None:
                        bm &= nb
                        return
                else:
                    ops, combine, verify = multi
                    acc = _native_scan_ops(col, ops, combine)
                    if acc is not None:
                        bm &= acc
                        if verify:
                            _native_verify(col, bm, self._pred)
                        return
        visit_values(bs, fld, bm, self._pred)

    def apply_to_values(self, get_values, nrows: int) -> np.ndarray:
        vals = get_values(canonical_field(self.field))
        return np.fromiter((self._pred(v) for v in vals), dtype=bool,
                           count=nrows)

    def needed_fields(self) -> set:
        return {canonical_field(self.field)}


# ---------------- composite filters ----------------

@dataclass(repr=False)
class FilterAnd(Filter):
    filters: list

    def apply_to_block(self, bs, bm):
        for f in self.filters:
            if not bm.any():
                return
            f.apply_to_block(bs, bm)

    def apply_to_values(self, get_values, nrows):
        mask = np.ones(nrows, dtype=bool)
        for f in self.filters:
            mask &= f.apply_to_values(get_values, nrows)
        return mask

    def needed_fields(self):
        out = set()
        for f in self.filters:
            out |= f.needed_fields()
        return out

    def to_string(self):
        parts = []
        for f in self.filters:
            s = f.to_string()
            if isinstance(f, FilterOr):
                s = f"({s})"
            parts.append(s)
        return " ".join(parts)


@dataclass(repr=False)
class FilterOr(Filter):
    filters: list

    def apply_to_block(self, bs, bm):
        acc = np.zeros(bs.nrows, dtype=bool)
        for f in self.filters:
            sub = bm.copy()
            f.apply_to_block(bs, sub)
            acc |= sub
            if acc.all():
                break
        bm &= acc

    def apply_to_values(self, get_values, nrows):
        mask = np.zeros(nrows, dtype=bool)
        for f in self.filters:
            mask |= f.apply_to_values(get_values, nrows)
        return mask

    def needed_fields(self):
        out = set()
        for f in self.filters:
            out |= f.needed_fields()
        return out

    def to_string(self):
        return " or ".join(
            f"({f.to_string()})" if isinstance(f, FilterOr) else f.to_string()
            for f in self.filters)


@dataclass(repr=False)
class FilterNot(Filter):
    inner: Filter

    def apply_to_block(self, bs, bm):
        sub = new_full_bitmap(bs.nrows)
        self.inner.apply_to_block(bs, sub)
        bm &= ~sub

    def apply_to_values(self, get_values, nrows):
        return ~self.inner.apply_to_values(get_values, nrows)

    def needed_fields(self):
        return self.inner.needed_fields()

    def to_string(self):
        s = self.inner.to_string()
        if isinstance(self.inner, (FilterAnd, FilterOr)):
            s = f"({s})"
        return f"!{s}"


def new_full_bitmap(n: int) -> np.ndarray:
    return np.ones(n, dtype=bool)


@dataclass(repr=False)
class FilterNoop(Filter):
    """Matches everything: `*`."""

    def apply_to_block(self, bs, bm):
        pass

    def apply_to_values(self, get_values, nrows):
        return np.ones(nrows, dtype=bool)

    def to_string(self):
        return "*"


@dataclass(repr=False)
class FilterNone(Filter):
    """Matches nothing (used for pruned subtrees)."""

    def apply_to_block(self, bs, bm):
        bm[:] = False

    def apply_to_values(self, get_values, nrows):
        return np.zeros(nrows, dtype=bool)

    def to_string(self):
        return "_none_"


# ---------------- word / phrase family ----------------

@dataclass(repr=False)
class FilterPhrase(_ValuePredFilter):
    field: str
    phrase: str

    def _pred(self, v):
        return match_phrase(v, self.phrase)

    def _scan_spec(self):
        if not self.phrase:
            return None
        return (self.phrase.encode("utf-8"), 0,
                is_word_char(self.phrase[0]),
                is_word_char(self.phrase[-1]))

    def _tokens(self):
        return tokenize_string(self.phrase)

    def to_string(self):
        return f"{_q(self.field)}{quote_str(self.phrase)}"


@dataclass(repr=False)
class FilterPrefix(_ValuePredFilter):
    field: str
    prefix: str

    def _pred(self, v):
        return match_prefix(v, self.prefix)

    def _scan_spec(self):
        if not self.prefix:
            return None
        return (self.prefix.encode("utf-8"), 1,
                is_word_char(self.prefix[0]), False)

    def _tokens(self):
        # trailing partial token can't be bloom-probed
        # (reference getTokensSkipLast — filter_prefix.go:354)
        toks = tokenize_string(self.prefix)
        if toks and self.prefix and (self.prefix[-1].isalnum()
                                     or self.prefix[-1] == "_"
                                     or not self.prefix[-1].isascii()):
            toks = toks[:-1]
        return toks

    def to_string(self):
        return f"{_q(self.field)}{quote_str(self.prefix)}*"


@dataclass(repr=False)
class FilterExact(_ValuePredFilter):
    field: str
    value: str

    def _pred(self, v):
        return v == self.value

    def _scan_spec(self):
        if not self.value:
            return None
        return (self.value.encode("utf-8"), 3, False, False)

    def _tokens(self):
        return tokenize_string(self.value)

    def apply_to_block(self, bs, bm):
        # numeric-column prune: a typed numeric column only decodes to
        # numeric strings, so a non-numeric or out-of-range exact value
        # can't match any row
        meta = bs.column_meta(canonical_field(self.field))
        if meta is not None and meta["t"] in _NUMERIC_VTS:
            v = parse_number(self.value)
            if math.isnan(v) or not (meta["min"] <= v <= meta["max"]):
                bm[:] = False
                return
        super().apply_to_block(bs, bm)

    def to_string(self):
        return f"{_q(self.field)}={quote_str(self.value)}"


@dataclass(repr=False)
class FilterExactPrefix(_ValuePredFilter):
    field: str
    prefix: str

    def _pred(self, v):
        return match_exact_prefix(v, self.prefix)

    def _scan_spec(self):
        if not self.prefix:
            return None
        return (self.prefix.encode("utf-8"), 4, False, False)

    def _tokens(self):
        toks = tokenize_string(self.prefix)
        return toks[:-1] if toks else []

    def to_string(self):
        return f"{_q(self.field)}={quote_str(self.prefix)}*"


@dataclass(repr=False)
class FilterAnyCasePhrase(_ValuePredFilter):
    field: str
    phrase: str

    def __post_init__(self):
        self._lower = self.phrase.lower()

    def _pred(self, v):
        return match_any_case_phrase(v, self._lower)

    def apply_to_block(self, bs, bm):
        fld = canonical_field(self.field)
        col = self._scan_column(bs, fld)
        if col is not None and self._lower and \
                _any_case_scan(col, self._lower, 0,
                               is_word_char(self._lower[0]),
                               is_word_char(self._lower[-1]),
                               self._pred, bm):
            return
        visit_values(bs, fld, bm, self._pred)

    def to_string(self):
        return f"{_q(self.field)}i({quote_str(self.phrase)})"


@dataclass(repr=False)
class FilterAnyCasePrefix(_ValuePredFilter):
    field: str
    prefix: str

    def __post_init__(self):
        self._lower = self.prefix.lower()

    def _pred(self, v):
        return match_any_case_prefix(v, self._lower)

    def apply_to_block(self, bs, bm):
        fld = canonical_field(self.field)
        col = self._scan_column(bs, fld)
        if col is not None and self._lower and \
                _any_case_scan(col, self._lower, 1,
                               is_word_char(self._lower[0]), False,
                               self._pred, bm):
            return
        visit_values(bs, fld, bm, self._pred)

    def to_string(self):
        return f"{_q(self.field)}i({quote_str(self.prefix)}*)"


@dataclass(repr=False)
class FilterRegexp(_ValuePredFilter):
    field: str
    pattern: str

    def __post_init__(self):
        self._re = re.compile(self.pattern)
        self._substr_literals = regex_literal_runs(self.pattern)
        self._bloom_tokens = regex_literal_tokens(self.pattern)
        # `A.*B` with literal A and B: decided per row natively (same
        # predicate the device plan uses — tpu/batch.py device_plan)
        parts = self.pattern.split(".*")
        self._pair = None
        if len(parts) == 2 and all(p and re.escape(p) == p
                                   for p in parts):
            self._pair = (parts[0].encode("utf-8"),
                          parts[1].encode("utf-8"))

    def _pred(self, v):
        return self._re.search(v) is not None

    def _tokens(self):
        return self._bloom_tokens

    def apply_to_block(self, bs, bm):
        # native literal prefilter: every match must contain ALL the
        # regex's mandatory literal runs (filter_regexp.go:44-51), so one
        # memmem pass per run prunes candidates and re.search runs only
        # on survivors — decoded individually from the arena, never as a
        # whole-column string list
        fld = canonical_field(self.field)
        if _bloom_prunes(bs, fld, self):
            bm[:] = False
            return
        lits = [t for t in self._substr_literals if t]
        col = self._scan_column(bs, fld) if (lits or self._pair) else None
        if col is not None:
            from .. import native
            if self._pair is not None:
                got = native.ordered_pair_scan_native(
                    col.arena, col.offsets, col.lengths, *self._pair)
                if got is not None:
                    definite, verify = got
                    bm &= definite | verify
                    self._verify_rows(col, bm, verify)
                    return
            cand = _native_scan_ops(
                col, [(lit.encode("utf-8"), 2, False, False)
                      for lit in lits], "and")
            if cand is not None:
                bm &= cand
                self._verify_rows(col, bm, None)
                return
        visit_values(bs, fld, bm, self._pred)

    def _verify_rows(self, col, bm, only) -> None:
        """re.search survivors; only: optional mask restricting which set
        rows need verification (others are already definite matches)."""
        if only is None:
            _native_verify(col, bm, self._pred)
            return
        check = bm & only
        _native_verify(col, check, self._pred)  # clears failed rows
        bm &= ~only | check

    def to_string(self):
        return f"{_q(self.field)}~{quote_str(self.pattern)}"


def regex_literal_tokens(pattern: str) -> list[str]:
    """Extract word tokens that every matching string must contain.

    The reference derives mandatory literals from the regex parse tree
    (regexutil GetLiterals — filter_regexp.go:44-51) and skips the first/last
    token (they may be partial words).  We conservatively extract maximal
    literal runs outside any metacharacter scope, then drop first/last token
    of each run boundary the same way.  These are sound for BLOOM probes
    (which index whole words); for plain substring prefilters use
    regex_literal_runs, which keeps the full runs.
    """
    out = []
    for lit, drop_last, is_final in _regex_literal_parts(pattern):
        toks = tokenize_string(lit)
        if not toks:
            continue
        start = 1 if (lit and (lit[0].isalnum() or lit[0] == "_")) else 0
        end = len(toks)
        if drop_last or not is_final:
            end -= 1
        else:
            if lit and (lit[-1].isalnum() or lit[-1] == "_"):
                end -= 1
        out.extend(toks[start:end])
    return out


def regex_literal_runs(pattern: str) -> list[str]:
    """Maximal literal substrings every match must contain, UNtokenized.

    Unlike the bloom tokens above, partial words are fine here: a device
    substring scan for "dead" soundly prefilters `~"dead.*exceeded"`."""
    return [lit for lit, _d, _f in _regex_literal_parts(pattern) if lit]


def _regex_literal_parts(pattern: str) -> list[tuple[str, bool, bool]]:
    """Shared scanner: (literal_run, last_char_dropped, is_final) parts."""
    # Inline flags/groups like (?i) change matching semantics for the whole
    # pattern (case folding etc.), so any literal we extract could wrongly
    # prune via blooms — bail to "no mandatory tokens" (the reference parses
    # the regex tree and folds case; we stay conservative).
    if "(?" in pattern:
        return []
    literals = []
    cur = []
    i, n = 0, len(pattern)
    depth_unsafe = 0
    while i < n:
        c = pattern[i]
        if c == "\\":
            e = pattern[i + 1] if i + 1 < n else ""
            # control escapes denote real characters, not the escape letter
            ctrl = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
                    "a": "\a", "0": "\0"}
            if e == "0" and i + 2 < n and pattern[i + 2] in "01234567":
                return []  # \0oo octal escape: stay conservative
            if e in ctrl:
                if depth_unsafe == 0:
                    cur.append(ctrl[e])
                i += 2
                continue
            # \xNN / \uNNNN / \UNNNNNNNN denote ONE character: decode it
            # (leaving the hex digits in the literal run silently pruned
            # real matches once this fed the native prefilter)
            if e in ("x", "u", "U"):
                width = {"x": 2, "u": 4, "U": 8}[e]
                hexs = pattern[i + 2:i + 2 + width]
                if len(hexs) != width or \
                        any(h not in "0123456789abcdefABCDEF"
                            for h in hexs):
                    return []  # malformed; re.compile rejects it anyway
                if depth_unsafe == 0:
                    cur.append(chr(int(hexs, 16)))
                i += 2 + width
                continue
            if e in "123456789":
                return []  # backreference: its text is unknown
            if e and e not in "wWdDsSbBAZ":
                if depth_unsafe == 0:
                    cur.append(e)
                i += 2
                continue
            # class escapes: unknown chars — break literal
            cur = _flush_literal(cur, literals, drop_last=True)
            i += 2
            continue
        if c in "|([{" :
            # alternation/group/class: everything inside is not mandatory
            if c == "|":
                if depth_unsafe == 0:
                    return []  # top-level alternation: nothing is mandatory
                i += 1
                continue
            if c == "{" and cur and depth_unsafe == 0:
                # quantifier may be {0,n}: the preceding char is optional
                cur.pop()
            cur = _flush_literal(cur, literals, drop_last=True)
            depth_unsafe += 1
            i += 1
            continue
        if c in ")]}":
            depth_unsafe = max(0, depth_unsafe - 1)
            cur = []
            i += 1
            continue
        if c in "*?+":
            # previous char is optional/repeated: drop it from the literal
            if cur and depth_unsafe == 0:
                cur.pop()
                cur = _flush_literal(cur, literals, drop_last=True)
            i += 1
            continue
        if c in ".^$":
            cur = _flush_literal(cur, literals, drop_last=True)
            i += 1
            continue
        if depth_unsafe == 0:
            cur.append(c)
        i += 1
    _flush_literal(cur, literals, drop_last=False, final=True)
    return literals


def _flush_literal(cur, literals, drop_last, final=False):
    if cur:
        literals.append(("".join(cur), drop_last, final))
    return []


# ---------------- multi-value filters ----------------

@dataclass(repr=False)
class FilterIn(_ValuePredFilter):
    field: str
    values: list
    subquery: object = None  # parsed Query, materialized by init_subqueries

    def __post_init__(self):
        self._set = set(self.values)

    def set_values(self, values):
        self.values = list(values)
        self._set = set(self.values)

    def _pred(self, v):
        return v in self._set

    def to_string(self):
        if self.subquery is not None:
            return f"{_q(self.field)}in({self.subquery.to_string()})"
        return f"{_q(self.field)}in({','.join(quote_str(v) for v in self.values)})"


@dataclass(repr=False)
class FilterContainsAll(_ValuePredFilter):
    field: str
    values: list
    subquery: object = None

    def set_values(self, values):
        self.values = list(values)

    def _pred(self, v):
        return all(match_phrase(v, p) for p in self.values)

    def _multi_scan_spec(self):
        if not self.values or any(not p for p in self.values):
            return None  # empty value: keep the Python semantics
        ops = [(p.encode("utf-8"), 0, is_word_char(p[0]),
                is_word_char(p[-1])) for p in self.values]
        return ops, "and", False

    def _tokens(self):
        out = []
        for p in self.values:
            out.extend(tokenize_string(p))
        return out

    def to_string(self):
        return (f"{_q(self.field)}contains_all("
                f"{','.join(quote_str(v) for v in self.values)})")


@dataclass(repr=False)
class FilterContainsAny(_ValuePredFilter):
    field: str
    values: list
    subquery: object = None

    def set_values(self, values):
        self.values = list(values)

    def _pred(self, v):
        return any(match_phrase(v, p) for p in self.values)

    def _multi_scan_spec(self):
        if not self.values or any(not p for p in self.values):
            return None
        ops = [(p.encode("utf-8"), 0, is_word_char(p[0]),
                is_word_char(p[-1])) for p in self.values]
        return ops, "or", False

    def to_string(self):
        return (f"{_q(self.field)}contains_any("
                f"{','.join(quote_str(v) for v in self.values)})")


@dataclass(repr=False)
class FilterSequence(_ValuePredFilter):
    field: str
    phrases: list

    def _pred(self, v):
        return match_sequence(v, self.phrases)

    def _multi_scan_spec(self):
        if not self.phrases or any(not p for p in self.phrases):
            return None
        # each phrase must appear at word boundaries (match_sequence uses
        # phrase_pos), so MODE_PHRASE prefilters are exact per phrase;
        # ORDER is checked by _pred on survivors when more than one
        ops = [(p.encode("utf-8"), 0, is_word_char(p[0]),
                is_word_char(p[-1])) for p in self.phrases]
        return ops, "and", len(self.phrases) > 1

    def _tokens(self):
        out = []
        for p in self.phrases:
            out.extend(tokenize_string(p))
        return out

    def to_string(self):
        return (f"{_q(self.field)}seq("
                f"{','.join(quote_str(v) for v in self.phrases)})")


# ---------------- range / numeric filters ----------------

@dataclass(repr=False)
class FilterRange(_ValuePredFilter):
    field: str
    min_value: float
    max_value: float
    repr_str: str = ""

    def _pred(self, v):
        return match_range(v, self.min_value, self.max_value)

    def apply_to_block(self, bs, bm):
        meta = bs.column_meta(canonical_field(self.field))
        if meta is not None and meta["t"] in _NUMERIC_VTS:
            # header-level prune + vectorized numeric compare
            if meta["max"] < self.min_value or meta["min"] > self.max_value:
                bm[:] = False
                return
            col = bs.column(canonical_field(self.field))
            nums = col.nums
            if nums.dtype == np.uint64:
                # integer-exact bounds: ceil the lower, floor the upper
                # (guarding inf: >x / <x filters carry infinite bounds)
                lo = 0 if self.min_value <= 0 else \
                    2**64 - 1 if math.isinf(self.min_value) else \
                    min(math.ceil(self.min_value), 2**64 - 1)
                hi = -1 if self.max_value < 0 else \
                    2**64 - 1 if math.isinf(self.max_value) else \
                    min(math.floor(self.max_value), 2**64 - 1)
                if lo > hi:
                    bm[:] = False
                    return
                mask = (nums >= np.uint64(lo)) & (nums <= np.uint64(hi))
            else:
                mask = (nums >= self.min_value) & (nums <= self.max_value)
            bm &= mask
            return
        super().apply_to_block(bs, bm)

    def to_string(self):
        if self.repr_str:
            return f"{_q(self.field)}{self.repr_str}"
        return f"{_q(self.field)}range[{self.min_value},{self.max_value}]"


@dataclass(repr=False)
class FilterStringRange(_ValuePredFilter):
    field: str
    min_value: str
    max_value: str
    repr_str: str = ""

    def _pred(self, v):
        return match_string_range(v, self.min_value, self.max_value)

    def to_string(self):
        if self.repr_str:
            return f"{_q(self.field)}{self.repr_str}"
        return (f"{_q(self.field)}string_range({quote_str(self.min_value)},"
                f"{quote_str(self.max_value)})")


@dataclass(repr=False)
class FilterLenRange(_ValuePredFilter):
    field: str
    min_len: int
    max_len: int

    def _pred(self, v):
        return match_len_range(v, self.min_len, self.max_len)

    def to_string(self):
        return f"{_q(self.field)}len_range({self.min_len},{self.max_len})"


@dataclass(repr=False)
class FilterIPv4Range(_ValuePredFilter):
    field: str
    min_value: int
    max_value: int

    def _pred(self, v):
        return match_ipv4_range(v, self.min_value, self.max_value)

    def apply_to_block(self, bs, bm):
        meta = bs.column_meta(canonical_field(self.field))
        if meta is not None and meta["t"] == VT_IPV4:
            col = bs.column(canonical_field(self.field))
            nums = col.nums
            bm &= (nums >= np.uint32(self.min_value)) & \
                  (nums <= np.uint32(self.max_value))
            return
        super().apply_to_block(bs, bm)

    def to_string(self):
        def ip(v):
            return f"{(v >> 24) & 255}.{(v >> 16) & 255}." \
                   f"{(v >> 8) & 255}.{v & 255}"
        return (f"{_q(self.field)}ipv4_range({ip(self.min_value)},"
                f"{ip(self.max_value)})")


@dataclass(repr=False)
class FilterValueType(Filter):
    field: str
    type_name: str

    def apply_to_block(self, bs, bm):
        if bs.value_type_name(canonical_field(self.field)) != self.type_name:
            bm[:] = False

    def apply_to_values(self, get_values, nrows):
        # in-pipeline values have lost their storage type; best effort: all
        # pass iff requesting 'string'
        keep = self.type_name == "string"
        return np.full(nrows, keep, dtype=bool)

    def needed_fields(self):
        return {canonical_field(self.field)}

    def to_string(self):
        return f"{_q(self.field)}value_type({self.type_name})"


# ---------------- cross-field filters ----------------

@dataclass(repr=False)
class FilterEqField(Filter):
    field: str
    other: str

    def apply_to_block(self, bs, bm):
        a = bs.values(canonical_field(self.field))
        b = bs.values(self.other)
        for i in np.nonzero(bm)[0]:
            if a[i] != b[i]:
                bm[i] = False

    def apply_to_values(self, get_values, nrows):
        a = get_values(self.field)
        b = get_values(self.other)
        return np.fromiter((x == y for x, y in zip(a, b)), dtype=bool,
                           count=nrows)

    def needed_fields(self):
        return {canonical_field(self.field), self.other}

    def to_string(self):
        return f"{_q(self.field)}eq_field({self.other})"


@dataclass(repr=False)
class FilterLeField(Filter):
    field: str
    other: str
    strict: bool = False  # True => lt_field

    def _cmp(self, x: str, y: str) -> bool:
        a, b = parse_number(x), parse_number(y)
        if not (math.isnan(a) or math.isnan(b)):
            return a < b if self.strict else a <= b
        return x < y if self.strict else x <= y

    def apply_to_block(self, bs, bm):
        a = bs.values(canonical_field(self.field))
        b = bs.values(self.other)
        for i in np.nonzero(bm)[0]:
            if not self._cmp(a[i], b[i]):
                bm[i] = False

    def apply_to_values(self, get_values, nrows):
        a = get_values(self.field)
        b = get_values(self.other)
        return np.fromiter((self._cmp(x, y) for x, y in zip(a, b)),
                           dtype=bool, count=nrows)

    def needed_fields(self):
        return {canonical_field(self.field), self.other}

    def to_string(self):
        fn = "lt_field" if self.strict else "le_field"
        return f"{_q(self.field)}{fn}({self.other})"


# ---------------- time / stream filters ----------------

@dataclass(repr=False)
class FilterTime(Filter):
    min_ts: int                      # inclusive, ns
    max_ts: int                      # inclusive, ns
    repr_str: str = ""

    def apply_to_block(self, bs, bm):
        if bs.part.block_min_ts(bs.block_idx) >= self.min_ts and \
           bs.part.block_max_ts(bs.block_idx) <= self.max_ts:
            return  # whole block inside the range
        ts = bs.timestamps()
        bm &= (ts >= self.min_ts) & (ts <= self.max_ts)

    def apply_to_values(self, get_values, nrows):
        from ..engine.block_result import parse_rfc3339
        vals = get_values("_time")
        out = np.zeros(nrows, dtype=bool)
        for i, v in enumerate(vals):
            t = parse_rfc3339(v)
            out[i] = t is not None and self.min_ts <= t <= self.max_ts
        return out

    def needed_fields(self):
        return {"_time"}

    def to_string(self):
        return f"_time:{self.repr_str}" if self.repr_str else \
            f"_time:[{self.min_ts},{self.max_ts}]"


@dataclass(repr=False)
class FilterDayRange(Filter):
    start_offset_ns: int   # offset into the day, inclusive
    end_offset_ns: int     # inclusive
    tz_offset_ns: int = 0
    repr_str: str = ""

    def apply_to_block(self, bs, bm):
        ts = bs.timestamps() + self.tz_offset_ns
        day_off = ts % (86400 * 1_000_000_000)
        bm &= (day_off >= self.start_offset_ns) & \
              (day_off <= self.end_offset_ns)

    def apply_to_values(self, get_values, nrows):
        from ..engine.block_result import parse_rfc3339
        vals = get_values("_time")
        out = np.zeros(nrows, dtype=bool)
        for i, v in enumerate(vals):
            t = parse_rfc3339(v)
            if t is None:
                continue
            off = (t + self.tz_offset_ns) % (86400 * 1_000_000_000)
            out[i] = self.start_offset_ns <= off <= self.end_offset_ns
        return out

    def needed_fields(self):
        return {"_time"}

    def to_string(self):
        return f"_time:day_range{self.repr_str}"


@dataclass(repr=False)
class FilterWeekRange(Filter):
    start_day: int   # 0=Sunday .. 6=Saturday, inclusive
    end_day: int
    tz_offset_ns: int = 0
    repr_str: str = ""

    def apply_to_block(self, bs, bm):
        ts = bs.timestamps() + self.tz_offset_ns
        # 1970-01-01 was a Thursday (weekday 4 with Sunday=0)
        days = ts // (86400 * 1_000_000_000)
        wd = (days + 4) % 7
        bm &= (wd >= self.start_day) & (wd <= self.end_day)

    def apply_to_values(self, get_values, nrows):
        from ..engine.block_result import parse_rfc3339
        vals = get_values("_time")
        out = np.zeros(nrows, dtype=bool)
        for i, v in enumerate(vals):
            t = parse_rfc3339(v)
            if t is None:
                continue
            wd = ((t + self.tz_offset_ns) // (86400 * 1_000_000_000) + 4) % 7
            out[i] = self.start_day <= wd <= self.end_day
        return out

    def needed_fields(self):
        return {"_time"}

    def to_string(self):
        return f"_time:week_range{self.repr_str}"


@dataclass(repr=False)
class FilterStream(Filter):
    """`{label="value", ...}` — resolved against the partition stream index."""

    stream_filter: object  # storage.stream_filter.StreamFilter

    def __post_init__(self):
        # per-partition resolution cache: id(partition) -> set[StreamID]
        self._resolved: dict = {}

    def resolve(self, partition, tenants) -> set:
        key = (id(partition), tuple(tenants))
        got = self._resolved.get(key)
        if got is None:
            got = set(partition.idb.search_stream_ids(list(tenants),
                                                      self.stream_filter))
            if len(self._resolved) > 64:
                self._resolved.clear()
            self._resolved[key] = got
        return got

    def apply_to_block(self, bs, bm):
        ctx = getattr(bs, "ctx", None)
        if ctx is None:
            return
        sids = self.resolve(ctx.partition, ctx.tenants)
        if bs.stream_id not in sids:
            bm[:] = False

    def apply_to_values(self, get_values, nrows):
        from ..storage.stream_filter import parse_stream_tags
        vals = get_values("_stream")
        out = np.zeros(nrows, dtype=bool)
        for i, v in enumerate(vals):
            out[i] = self.stream_filter.matches(parse_stream_tags(v))
        return out

    def needed_fields(self):
        return {"_stream"}

    def to_string(self):
        return self.stream_filter.to_string()


@dataclass(repr=False)
class FilterStreamID(Filter):
    stream_ids: list  # hex strings

    def __post_init__(self):
        self._set = set(self.stream_ids)

    def apply_to_block(self, bs, bm):
        if bs.stream_id.as_string() not in self._set:
            bm[:] = False

    def apply_to_values(self, get_values, nrows):
        vals = get_values("_stream_id")
        return np.fromiter((v in self._set for v in vals), dtype=bool,
                           count=nrows)

    def needed_fields(self):
        return {"_stream_id"}

    def to_string(self):
        if len(self.stream_ids) == 1:
            return f"_stream_id:{self.stream_ids[0]}"
        return "_stream_id:in(" + ",".join(self.stream_ids) + ")"
