"""Duration and timestamp-bound parsing for LogsQL time filters."""

from __future__ import annotations

import re

NS = 1_000_000_000

# shared partial-RFC3339 shape: year down to optional nanos + optional tz.
# Used both for parsing (engine.block_result) and for bound widening here —
# one pattern so the two can never disagree on what's a valid timestamp.
PARTIAL_RFC3339_RE = re.compile(
    r"^(\d{4})(?:-(\d{2})(?:-(\d{2})(?:[T ](\d{2})(?::(\d{2})"
    r"(?::(\d{2})(?:\.(\d{1,9}))?)?)?)?)?)?"
    r"(Z|[+-]\d{2}:?\d{2})?$")

_DUR_UNITS = {
    "ns": 1, "us": 1_000, "µs": 1_000, "ms": 1_000_000,
    "s": NS, "m": 60 * NS, "h": 3600 * NS, "d": 86400 * NS,
    "w": 7 * 86400 * NS, "y": 365 * 86400 * NS,
}

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h|d|w|y)")


def parse_duration(s: str) -> int | None:
    """Parse `1h30m`-style durations into ns; None if not a duration."""
    if not s:
        return None
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    pos = 0
    total = 0.0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            return None
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        return None
    return int(-total if neg else total)


def is_duration_like(s: str) -> bool:
    return parse_duration(s) is not None


def ts_bounds(s: str) -> tuple[int, int] | None:
    """Bounds [start, end] (inclusive ns) of a possibly-partial timestamp.

    `2024` covers the year, `2024-01-02` the day, a full RFC3339 stamp covers
    exactly one ns.  Mirrors how the reference widens partial timestamps in
    _time filters (parser.go parseFilterTime).
    """
    from ..engine.block_result import parse_rfc3339
    from ..storage.values_encoder import _days_in_month
    m = PARTIAL_RFC3339_RE.match(s)
    if m is None:
        return None
    start = parse_rfc3339(s)
    if start is None:
        return None
    y, mo, d, h, mi, sec, frac, _tz = m.groups()
    if frac is not None:
        span = 10 ** (9 - len(frac))
    elif sec is not None:
        span = NS
    elif mi is not None:
        span = 60 * NS
    elif h is not None:
        span = 3600 * NS
    elif d is not None:
        span = 86400 * NS
    elif mo is not None:
        span = _days_in_month(int(y), int(mo)) * 86400 * NS
    else:
        yy = int(y)
        leap = yy % 4 == 0 and (yy % 100 != 0 or yy % 400 == 0)
        span = (366 if leap else 365) * 86400 * NS
    return start, start + span - 1
