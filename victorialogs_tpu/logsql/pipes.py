"""LogsQL pipes: AST + streaming processors.

Reference contract (lib/logstorage/pipe.go:11-82): each pipe parses itself,
reports needed/updated fields, and spawns a pipeProcessor that receives
column-oriented blocks and flushes accumulated state downstream.  Stateless
pipes stream block-by-block; stateful ones (sort/stats/uniq/top) accumulate
and emit at flush.  `limit` cancels the upstream scan once satisfied
(reference runPipes per-pipe cancellation — storage_search.go:147-185).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from functools import cmp_to_key

import numpy as np

from ..engine.block_result import BlockResult
from .duration import parse_duration
from .lexer import Lexer, quote_token_if_needed
from .matchers import parse_number
from . import stats_funcs as sf


class ParseError(ValueError):
    pass


# ---------------- processor plumbing ----------------

class Processor:
    def __init__(self, next_p):
        self.next_p = next_p

    def write_block(self, br: BlockResult) -> None:
        self.next_p.write_block(br)

    def flush(self) -> None:
        self.next_p.flush()

    def is_done(self) -> bool:
        return self.next_p.is_done() if self.next_p else False


class SinkProcessor(Processor):
    """Terminal processor: hands blocks to a callback."""

    def __init__(self, write_fn):
        super().__init__(None)
        self.write_fn = write_fn
        self._done = False

    def write_block(self, br):
        if self.write_fn(br) is False:
            self._done = True

    def flush(self):
        pass

    def is_done(self):
        return self._done


class Pipe:
    name = "?"

    def to_string(self) -> str:
        raise NotImplementedError

    def can_live_tail(self) -> bool:
        return False

    def needed_fields(self) -> set:
        return set()

    def input_fields(self, out_needed: set) -> set:
        """Fields this pipe needs from its INPUT given the fields needed
        from its output — the back-to-front needed-columns propagation
        (reference per-pipe updateNeededFields + lib/prefixfilter).  The
        default (pass-through + own inputs) is always safe; reducing pipes
        override it to reset the set."""
        return out_needed | self.needed_fields()

    def make_processor(self, next_p: Processor) -> Processor:
        raise NotImplementedError

    def __repr__(self):
        return f"<pipe {self.to_string()}>"

    def split_to_remote_and_local(self):
        """(remote_pipe|None, local_pipes) for cluster pushdown
        (reference pipe.splitToRemoteAndLocal — pipe.go:15-22)."""
        return None, [self]


# ---------------- fields / delete / copy / rename ----------------

def expand_field_patterns(patterns: list, names: list) -> list:
    """Expand trailing-`*` wildcards against available column names
    (reference lib/prefixfilter wildcard selections)."""
    out: dict[str, None] = {}
    for p in patterns:
        if p.endswith("*"):
            prefix = p[:-1]
            for n in names:
                if n.startswith(prefix):
                    out.setdefault(n, None)
        else:
            out.setdefault(p, None)
    return list(out)


@dataclass(repr=False)
class PipeFields(Pipe):
    fields: list

    name = "fields"

    def to_string(self):
        return "fields " + ", ".join(quote_token_if_needed(f)
                                     for f in self.fields)

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return set(self.fields)

    def input_fields(self, out_needed):
        if any(f.endswith("*") for f in self.fields):
            return {"*"}
        return set(self.fields)

    def make_processor(self, next_p):
        fields = self.fields
        has_wildcard = any(f.endswith("*") for f in fields)

        class P(Processor):
            def write_block(self, br):
                use = expand_field_patterns(fields, br.column_names()) \
                    if has_wildcard else fields
                # restrict, don't materialize: storage-backed blocks
                # keep their typed columnar access so the NDJSON emit
                # sink never sees per-row string lists (engine/emit.py)
                self.next_p.write_block(br.restrict_fields(use))
        return P(next_p)

    def split_to_remote_and_local(self):
        return self, [self]


@dataclass(repr=False)
class PipeDelete(Pipe):
    fields: list

    name = "delete"

    def to_string(self):
        return "delete " + ", ".join(quote_token_if_needed(f)
                                     for f in self.fields)

    def input_fields(self, out_needed):
        if "*" in out_needed:
            return out_needed
        return out_needed - set(self.fields)

    def can_live_tail(self):
        return True

    def make_processor(self, next_p):
        drop = set(self.fields)

        class P(Processor):
            def write_block(self, br):
                names = [n for n in br.column_names() if n not in drop]
                self.next_p.write_block(br.restrict_fields(names))
        return P(next_p)


@dataclass(repr=False)
class PipeCopy(Pipe):
    pairs: list  # [(src, dst)]

    name = "copy"

    def to_string(self):
        return "copy " + ", ".join(f"{s} as {d}" for s, d in self.pairs)

    def can_live_tail(self):
        return True

    def input_fields(self, out_needed):
        # the processor reads EVERY pair's src from the ORIGINAL block
        # (parallel semantics), so a needed dst requires its src as-is —
        # no sequential substitution through chained pairs
        if "*" in out_needed:
            return out_needed
        out = set(out_needed)
        for _s, d in self.pairs:
            out.discard(d)          # produced/overwritten by the copy
        for s, d in self.pairs:
            if d in out_needed:
                out.add(s)
        return out

    def make_processor(self, next_p):
        pairs = self.pairs

        class P(Processor):
            def write_block(self, br):
                out = br.materialize()
                for s, d in pairs:
                    out._cols[d] = list(br.column(s))
                self.next_p.write_block(out)
        return P(next_p)


@dataclass(repr=False)
class PipeRename(Pipe):
    pairs: list

    name = "rename"

    def to_string(self):
        return "rename " + ", ".join(f"{s} as {d}" for s, d in self.pairs)

    def can_live_tail(self):
        return True

    def input_fields(self, out_needed):
        # dst maps back to src; the src name itself no longer exists
        # downstream (rename removes it), so it is only needed via dst
        if "*" in out_needed:
            return out_needed
        out = set(out_needed)
        for s, d in reversed(self.pairs):
            if d in out:
                out.discard(d)
                out.add(s)
            else:
                out.discard(s)
        return out

    def make_processor(self, next_p):
        pairs = self.pairs

        class P(Processor):
            def write_block(self, br):
                out = br.materialize()
                for s, d in pairs:
                    vals = out._cols.pop(s, None)
                    if vals is None:
                        vals = br.column(s)
                    out._cols[d] = vals
                self.next_p.write_block(out)
        return P(next_p)


# ---------------- limit / offset ----------------

@dataclass(repr=False)
class PipeLimit(Pipe):
    n: int

    name = "limit"

    def to_string(self):
        return f"limit {self.n}"

    def make_processor(self, next_p):
        limit = self.n

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.seen = 0

            def write_block(self, br):
                if self.seen >= limit:
                    return
                take = min(br.nrows, limit - self.seen)
                self.seen += take
                if take < br.nrows:
                    mask = np.zeros(br.nrows, dtype=bool)
                    mask[:take] = True
                    br = br.filter_rows(mask)
                self.next_p.write_block(br)

            def is_done(self):
                return self.seen >= limit or super().is_done()
        return P(next_p)


@dataclass(repr=False)
class PipeOffset(Pipe):
    n: int

    name = "offset"

    def to_string(self):
        return f"offset {self.n}"

    def make_processor(self, next_p):
        offset = self.n

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                self.skipped = 0

            def write_block(self, br):
                if self.skipped >= offset:
                    self.next_p.write_block(br)
                    return
                skip = min(br.nrows, offset - self.skipped)
                self.skipped += skip
                if skip < br.nrows:
                    mask = np.zeros(br.nrows, dtype=bool)
                    mask[skip:] = True
                    self.next_p.write_block(br.filter_rows(mask))
        return P(next_p)


# ---------------- where / filter ----------------

@dataclass(repr=False)
class PipeWhere(Pipe):
    filter: object  # logsql.filters.Filter

    name = "filter"

    def to_string(self):
        return f"filter {self.filter.to_string()}"

    def can_live_tail(self):
        return True

    def needed_fields(self):
        return self.filter.needed_fields()

    def make_processor(self, next_p):
        flt = self.filter

        class P(Processor):
            def write_block(self, br):
                bs = getattr(br, "_bs", None)
                # restricted views never take the block path: the filter
                # may reference a projected-out field, which must read
                # "" (fields-pipe semantics), not the storage value
                if bs is not None and not br._cols and \
                        br._restrict is None:
                    # storage-backed rows: evaluate through the block path
                    # (bloom kill-path + native arena scans) and slice the
                    # full-block bitmap through the selection — identical
                    # semantics to per-value apply (both use _pred)
                    import numpy as np
                    full = np.ones(bs.nrows, dtype=bool)
                    flt.apply_to_block(bs, full)
                    mask = full[br._sel]
                else:
                    mask = flt.apply_to_values(br.column, br.nrows)
                if mask.all():
                    self.next_p.write_block(br)
                elif mask.any():
                    self.next_p.write_block(br.filter_rows(mask))
        return P(next_p)


# ---------------- sort ----------------

def _cmp_values(a: str, b: str) -> int:
    fa, fb = parse_number(a), parse_number(b)
    na, nb = not math.isnan(fa), not math.isnan(fb)
    if na and nb:
        if fa < fb:
            return -1
        if fa > fb:
            return 1
        return -1 if a < b else (1 if a > b else 0)
    if na:
        return -1
    if nb:
        return 1
    if a and b and a[0].isdigit() and b[0].isdigit():
        # RFC3339Nano trims fractions, so "..00Z" vs "..00.5Z" mis-sorts
        # lexicographically ('.' < 'Z'); compare as timestamps when both
        # parse
        from ..engine.block_result import parse_rfc3339
        ta, tb = parse_rfc3339(a), parse_rfc3339(b)
        if ta is not None and tb is not None and ta != tb:
            return -1 if ta < tb else 1
    return -1 if a < b else (1 if a > b else 0)


@dataclass(repr=False)
class PipeSort(Pipe):
    by: list            # [(field, desc)]
    desc: bool = False  # global desc
    limit: int = 0
    offset: int = 0
    rank_field: str = ""
    partition_by: list = dc_field(default_factory=list)

    name = "sort"

    def to_string(self):
        s = "sort"
        if self.by:
            s += " by (" + ", ".join(
                f + (" desc" if d else "") for f, d in self.by) + ")"
        if self.desc:
            s += " desc"
        if self.partition_by:
            s += " partition by (" + ", ".join(self.partition_by) + ")"
        if self.offset:
            s += f" offset {self.offset}"
        if self.limit:
            s += f" limit {self.limit}"
        if self.rank_field:
            s += f" rank as {self.rank_field}"
        return s

    def needed_fields(self):
        return {f for f, _ in self.by} | set(self.partition_by)

    def make_processor(self, next_p):
        if self.partition_by:
            return self._make_partitioned_processor(next_p)
        if self.limit > 0:
            return self._make_topk_processor(next_p)
        return self._make_full_processor(next_p)

    def _make_partitioned_processor(self, next_p):
        """offset/limit apply PER partition-key group (reference
        pipe_sort.go partitionByFields — e.g. per-field top values in the
        facets split)."""
        pipe = self
        keyfn = cmp_to_key(self._sort_cmp())

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                from ..utils.memory import MemoryBudget
                self.budget = MemoryBudget(0.2, "sort")
                # partition key -> list of (sort_keys, seq, row_dict)
                self.groups: dict[tuple, list] = {}
                self.seq = 0

            def write_block(self, br):
                cols = [br.column(f) for f, _ in pipe.by]
                pcols = [br.column(f) for f in pipe.partition_by]
                names = br.column_names()
                all_cols = [(n, br.column(n)) for n in names]
                self.budget.add(sum(
                    sum(len(v) + 8 for v in vals)
                    for _n, vals in all_cols) + 64)
                for ri in range(br.nrows):
                    pkey = tuple(c[ri] for c in pcols)
                    self.groups.setdefault(pkey, []).append(
                        ([c[ri] for c in cols], self.seq,
                         {n: v[ri] for n, v in all_cols}))
                    self.seq += 1

            def flush(self):
                out_rows: list[dict] = []
                for pkey in sorted(self.groups):
                    rows = sorted(self.groups[pkey],
                                  key=lambda r: (keyfn(r), r[1]))
                    if pipe.offset:
                        rows = rows[pipe.offset:]
                    if pipe.limit:
                        rows = rows[:pipe.limit]
                    for i, (_k, _s, rd) in enumerate(rows):
                        if pipe.rank_field:
                            rd = {**rd,
                                  pipe.rank_field: str(pipe.offset + 1 + i)}
                        out_rows.append(rd)
                if out_rows:
                    names: dict[str, None] = {}
                    for rd in out_rows:
                        for n in rd:
                            names.setdefault(n, None)
                    cols = {n: [rd.get(n, "") for rd in out_rows]
                            for n in names}
                    self.next_p.write_block(BlockResult.from_columns(cols))
                else:
                    self.next_p.write_block(BlockResult(0))
                self.groups = {}
                self.next_p.flush()
        return P(next_p)

    def _sort_cmp(self):
        pipe = self

        def cmp(x, y):
            # global desc reverses the whole ordering, including
            # per-field desc flags (effective desc = field XOR global)
            for k, (_f, d) in enumerate(pipe.by):
                c = _cmp_values(x[0][k], y[0][k])
                if c:
                    return -c if (d != pipe.desc) else c
            return 0
        return cmp

    def _make_topk_processor(self, next_p):
        """Bounded top-k sort: `sort ... limit N` keeps only offset+N rows
        (reference pipe_sort_topk.go) instead of materializing everything."""
        import heapq
        pipe = self
        k = self.limit + self.offset
        keyfn = cmp_to_key(self._sort_cmp())

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                # (key_values, seq, name->idx map shared per block,
                #  value tuple) — typed columnar access without a dict
                # per retained row
                self.top: list = []
                self.seq = 0

            def write_block(self, br):
                cols = [br.column(f) for f, _ in pipe.by]
                names = br.column_names()
                all_cols = [br.column(n) for n in names]
                idx = {n: j for j, n in enumerate(names)}
                rows = []
                for ri in range(br.nrows):
                    rows.append(([c[ri] for c in cols], self.seq, idx,
                                 [v[ri] for v in all_cols]))
                    self.seq += 1
                self.top = heapq.nsmallest(
                    k, self.top + rows,
                    key=lambda r: (keyfn(r), r[1]))

            def flush(self):
                rows = self.top[pipe.offset:]
                rank0 = pipe.offset + 1
                names: dict[str, None] = {}
                for _kv, _s, idx, _vals in rows:
                    for n in idx:
                        names.setdefault(n, None)
                out_cols = {
                    n: [vals[idx[n]] if n in idx else ""
                        for _kv, _s, idx, vals in rows]
                    for n in names}
                if pipe.rank_field:
                    out_cols[pipe.rank_field] = [
                        str(rank0 + i) for i in range(len(rows))]
                self.next_p.write_block(
                    BlockResult.from_columns(out_cols)
                    if out_cols else BlockResult(0))
                self.top = []
                self.next_p.flush()
        return P(next_p)

    def _make_full_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                from ..utils.memory import MemoryBudget
                self.blocks: list[BlockResult] = []
                self.budget = MemoryBudget(0.2, "sort")

            def write_block(self, br):
                br = br.materialize()
                self.budget.add(sum(
                    sum(len(v) + 8 for v in vals)
                    for vals in br._cols.values()) + 64)
                self.blocks.append(br)

            def flush(self):
                rows = []  # (sort_key_values, block_idx, row_idx)
                for bi, br in enumerate(self.blocks):
                    cols = [br.column(f) for f, _ in pipe.by]
                    for ri in range(br.nrows):
                        rows.append(([c[ri] for c in cols], bi, ri))

                rows.sort(key=cmp_to_key(pipe._sort_cmp()))
                if pipe.offset:
                    rows = rows[pipe.offset:]
                if pipe.limit:
                    rows = rows[:pipe.limit]
                # emit in sorted order, with optional rank column
                rank0 = pipe.offset + 1
                out_cols: dict[str, list[str]] = {}
                names: dict[str, None] = {}
                for br in self.blocks:
                    for n in br.column_names():
                        names.setdefault(n, None)
                for n in names:
                    col = []
                    for _k, bi, ri in rows:
                        col.append(self.blocks[bi].column(n)[ri])
                    out_cols[n] = col
                if pipe.rank_field:
                    out_cols[pipe.rank_field] = [
                        str(rank0 + i) for i in range(len(rows))]
                if rows or not self.blocks:
                    self.next_p.write_block(
                        BlockResult.from_columns(out_cols)
                        if out_cols else BlockResult(0))
                self.blocks = []
                self.next_p.flush()
        return P(next_p)


# ---------------- uniq ----------------

@dataclass(repr=False)
class PipeUniq(Pipe):
    by: list
    limit: int = 0
    with_hits: bool = False

    name = "uniq"

    def to_string(self):
        s = "uniq"
        if self.by:
            s += " by (" + ", ".join(self.by) + ")"
        if self.with_hits:
            s += " with hits"
        if self.limit:
            s += f" limit {self.limit}"
        return s

    def needed_fields(self):
        return set(self.by)

    def input_fields(self, out_needed):
        return set(self.by) if self.by else {"*"}

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                from ..utils.memory import MemoryBudget
                # keys are (field, value) pair tuples (empty values dropped)
                # so blocks with different column sets mix safely
                self.seen: dict[tuple, int] = {}
                self.budget = MemoryBudget(0.4, "uniq")

            def write_block(self, br):
                if pipe.limit and len(self.seen) > pipe.limit:
                    return  # limit exceeded: stop accumulating
                if len(pipe.by) == 1 and \
                        hasattr(br, "dict_value_counts"):
                    # typed fast path for one const/dict by-column
                    f = pipe.by[0]
                    pairs = br.dict_value_counts(f)
                    if pairs is not None:
                        for v, cnt in pairs:
                            key = ((f, v),) if v != "" else ()
                            if key not in self.seen:
                                self.seen[key] = cnt
                                self.budget.add(len(f) + len(v) + 80)
                            else:
                                self.seen[key] += cnt
                        return
                fields = pipe.by or br.column_names()
                cols = [(f, br.column(f)) for f in fields]
                for ri in range(br.nrows):
                    key = tuple((f, c[ri]) for f, c in cols if c[ri] != "")
                    if key not in self.seen:
                        self.seen[key] = 1
                        self.budget.add(sum(
                            len(f) + len(v) for f, v in key) + 80)
                    else:
                        self.seen[key] += 1

            def is_done(self):
                if pipe.limit and len(self.seen) > pipe.limit:
                    return True  # cancels the upstream scan
                return super().is_done()

            def flush(self):
                exceeded = pipe.limit and len(self.seen) > pipe.limit
                keys = sorted(self.seen)
                if pipe.limit:
                    keys = keys[:pipe.limit]
                names: dict[str, None] = {f: None for f in pipe.by}
                for k in keys:
                    for f, _v in k:
                        names.setdefault(f, None)
                cols = {f: [dict(k).get(f, "") for k in keys]
                        for f in names}
                if pipe.with_hits:
                    # past the limit the counts are incomplete: the
                    # reference zeroes them rather than lying
                    cols["hits"] = ["0" if exceeded else str(self.seen[k])
                                    for k in keys]
                self.next_p.write_block(BlockResult.from_columns(cols)
                                        if keys else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


# ---------------- stats ----------------

_NS_DAY = 86400 * 1_000_000_000


def truncate_timestamp(ts: int, b: "ByField") -> int | None:
    """Reference truncateTimestamp (block_result.go:818): fixed-size
    buckets plus calendar week (Monday-start), month and year."""
    name = b.bucket.lower()
    off = b.offset_ns()
    if name == "week":
        # adjust so weeks start on Monday (epoch day 0 was a Thursday)
        off += 4 * _NS_DAY
        step = 7 * _NS_DAY
        return ((ts - off) // step) * step + off
    if name in ("month", "year"):
        import datetime
        t = ts - off
        dt = datetime.datetime.fromtimestamp(t / 1e9,
                                             tz=datetime.timezone.utc)
        if name == "month":
            start = datetime.datetime(dt.year, dt.month, 1,
                                      tzinfo=datetime.timezone.utc)
        else:
            start = datetime.datetime(dt.year, 1, 1,
                                      tzinfo=datetime.timezone.utc)
        return int(start.timestamp()) * 1_000_000_000 + off
    step = parse_duration(b.bucket)
    if not step:
        return None
    return ((ts - off) // step) * step + off

@dataclass(repr=False)
class ByField:
    name: str
    bucket: str = ""     # e.g. "5m" or "10" for numeric buckets
    bucket_offset: str = ""

    def to_string(self):
        s = self.name
        if self.bucket:
            s += f":{self.bucket}"
            if self.bucket_offset:
                s += f" offset {self.bucket_offset}"
        return s

    def offset_ns(self) -> int:
        if not self.bucket_offset:
            return 0
        d = parse_duration(self.bucket_offset)
        return d if d is not None else 0


@dataclass(repr=False)
class PipeStats(Pipe):
    by: list            # list[ByField]
    funcs: list         # list[StatsFunc]

    name = "stats"

    def to_string(self):
        s = "stats"
        if self.by:
            s += " by (" + ", ".join(b.to_string() for b in self.by) + ")"
        s += " " + ", ".join(f.to_string() for f in self.funcs)
        return s

    def needed_fields(self):
        out = {b.name for b in self.by}
        for f in self.funcs:
            out |= f.needed_fields()
        return out

    def input_fields(self, out_needed):
        # stats replaces the row set: only grouped/aggregated inputs matter
        return self.needed_fields()

    def _bucket_value(self, b: ByField, v: str, ts: int | None) -> str:
        if not b.bucket:
            return v
        if b.name == "_time":
            if ts is not None:
                t = truncate_timestamp(ts, b)
                if t is not None:
                    from ..engine.block_result import format_rfc3339
                    return format_rfc3339(t)
            return v
        step = parse_number(b.bucket)
        if not math.isnan(step) and step > 0:
            f = parse_number(v)
            if not math.isnan(f):
                off = parse_number(b.bucket_offset) \
                    if b.bucket_offset else 0.0
                if math.isnan(off):
                    off = 0.0
                return sf.format_number(
                    math.floor((f - off) / step) * step + off)
        return v

    def make_processor(self, next_p):
        pipe = self

        class P(Processor):
            def __init__(self, np_):
                super().__init__(np_)
                from ..utils.memory import MemoryBudget
                # group key -> list[state per func]
                self.groups: dict[tuple, list] = {}
                self.budget = MemoryBudget(0.3, "stats")
                for fn in pipe.funcs:
                    fn.budget = self.budget

            def _key_columns(self, br, skip=()):
                """Per-row group-key value lists (bucketing applied).

                _time:step buckets vectorize over the int64 timestamps —
                only distinct buckets pay string formatting (the per-row
                Python path was the hits-endpoint hot loop).
                skip: by-field indices the caller handles itself (dict
                codes) — their slot is None, nothing materializes."""
                n = br.nrows
                # array form only when a bucketed _time key needs it
                ts = br.timestamps_np() if any(
                    b.bucket and b.name == "_time" for b in pipe.by) \
                    else None
                key_cols = []
                for ci, b in enumerate(pipe.by):
                    if ci in skip:
                        key_cols.append(None)
                        continue
                    if b.bucket and b.name == "_time" and ts is not None \
                            and b.bucket.lower() not in ("week", "month",
                                                         "year"):
                        step = parse_duration(b.bucket)
                        if step:
                            arr = np.asarray(ts, dtype=np.int64)
                            off = b.offset_ns()
                            bucketed = ((arr - off) // step) * step + off
                            uniq, inv = np.unique(bucketed,
                                                  return_inverse=True)
                            from ..engine.block_result import format_rfc3339
                            strs = [format_rfc3339(int(t)) for t in uniq]
                            key_cols.append([strs[j] for j in inv])
                            continue
                    vals = br.column(b.name)
                    if b.bucket:
                        vals = [pipe._bucket_value(
                            b, vals[i],
                            ts[i] if (ts is not None
                                      and b.name == "_time") else None)
                            for i in range(n)]
                    key_cols.append(vals)
                return key_cols

            def _try_fast_count(self, br) -> bool:
                """Vectorized `count() by (...)`: bincount over factorized
                group ids — the device-partials analogue on the host side
                (block bitmaps come from the TPU; per-group counting needs
                no per-row Python)."""
                if any(fn.iff is not None or fn.fields or
                       not isinstance(fn, sf.StatsCount)
                       for fn in pipe.funcs):
                    return False
                n = br.nrows
                if not pipe.by:
                    key = ()
                    states = self.groups.get(key)
                    if states is None:
                        states = [fn.new_state() for fn in pipe.funcs]
                        self.groups[key] = states
                        self.budget.add(80)
                    for k in range(len(pipe.funcs)):
                        states[k] += n
                    return True
                # dict-encoded by-columns factorize through their stored
                # codes — no per-row Python, no string materialization
                # (typed lazy columns, block_result.go:26-63)
                dict_cols = {}
                for ci, b in enumerate(pipe.by):
                    if not b.bucket and hasattr(br, "dict_column"):
                        dc = br.dict_column(b.name)
                        if dc is not None:
                            dict_cols[ci] = dc
                key_cols = self._key_columns(br, skip=dict_cols)
                # factorize each key column; bail to the generic path when
                # the dense code space would blow up (multiple
                # high-cardinality by-fields)
                codes = np.zeros(n, dtype=np.int64)
                uniques_per_col = []
                stride = 1
                for ci in range(len(pipe.by)):
                    if ci in dict_cols:
                        ids, dvals = dict_cols[ci]
                        nuniq = len(dvals)
                        col_codes = ids.astype(np.int64)
                        uniq_map = dict(enumerate(dvals))
                    else:
                        vals = key_cols[ci]
                        mapping: dict = {}
                        col_codes = np.empty(n, dtype=np.int64)
                        for i, v in enumerate(vals):
                            c = mapping.get(v)
                            if c is None:
                                c = mapping[v] = len(mapping)
                            col_codes[i] = c
                        nuniq = len(mapping)
                        uniq_map = {c: v for v, c in mapping.items()}
                    stride *= max(nuniq, 1)
                    if stride > max(4 * n, 1 << 16):
                        return False
                    codes = codes * max(nuniq, 1) + col_codes
                    uniques_per_col.append(uniq_map)
                counts = np.bincount(codes, minlength=0)
                for code in np.nonzero(counts)[0]:
                    cnt = int(counts[code])
                    parts = []
                    rem = int(code)
                    for uniq in reversed(uniques_per_col):
                        parts.append(uniq[rem % len(uniq)])
                        rem //= len(uniq)
                    key = tuple(reversed(parts))
                    states = self.groups.get(key)
                    if states is None:
                        states = [fn.new_state() for fn in pipe.funcs]
                        self.groups[key] = states
                        self.budget.add(sum(len(k) for k in key) + 80)
                    for k in range(len(pipe.funcs)):
                        states[k] += cnt
                return True

            def write_block(self, br):
                n = br.nrows
                if n == 0:
                    return
                if self._try_fast_count(br):
                    return
                # group keys per row
                if pipe.by:
                    key_cols = self._key_columns(br)
                    rows_by_key: dict[tuple, list] = {}
                    for i in range(n):
                        rows_by_key.setdefault(
                            tuple(c[i] for c in key_cols), []).append(i)
                else:
                    rows_by_key = {(): list(range(n))}
                func_cols = [fn.block_cols(br) for fn in pipe.funcs]
                # per-func `if (...)` row guards
                iff_masks = [None if fn.iff is None
                             else fn.iff.apply_to_values(br.column, n)
                             for fn in pipe.funcs]
                for key, idxs in rows_by_key.items():
                    states = self.groups.get(key)
                    if states is None:
                        states = [fn.new_state() for fn in pipe.funcs]
                        self.groups[key] = states
                        self.budget.add(
                            sum(len(k) for k in key) + 80)
                    for k, fn in enumerate(pipe.funcs):
                        use = idxs if iff_masks[k] is None else \
                            [i for i in idxs if iff_masks[k][i]]
                        states[k] = fn.update(states[k], func_cols[k], use)

            def absorb_partials(self, key: tuple, states: list) -> None:
                """Merge device-computed partial states for one group
                (tpu/stats_device.py) — the in-process analogue of the
                cluster importState merge (pipe_stats.go:93-125).

                Set-valued states (count_uniq) and list-valued states
                (quantile/median value lists) charge the memory budget
                on actual growth, matching the host update path
                (pipe_stats.go:314-348)."""
                def set_cost(s) -> int:
                    if isinstance(s, list):
                        return 32 * len(s)
                    return sum(sum(len(x) for x in k) + 64 for k in s)

                cur = self.groups.get(key)
                if cur is None:
                    self.groups[key] = states
                    self.budget.add(sum(len(k) for k in key) + 80 +
                                    sum(set_cost(st) for st in states
                                        if isinstance(st, (set, list))))
                else:
                    for k, fn in enumerate(pipe.funcs):
                        before = len(cur[k]) \
                            if isinstance(cur[k], (set, list)) else None
                        cur[k] = fn.merge(cur[k], states[k])
                        if before is not None and len(cur[k]) > before:
                            self.budget.add(set_cost(states[k]))

            def flush(self):
                by_names = [b.name for b in pipe.by]
                keys = sorted(self.groups)
                cols: dict[str, list[str]] = {n: [] for n in by_names}
                for fn in pipe.funcs:
                    cols[fn.out_name] = []
                for key in keys:
                    for n, kv in zip(by_names, key):
                        cols[n].append(kv)
                    states = self.groups[key]
                    for fn, st in zip(pipe.funcs, states):
                        cols[fn.out_name].append(fn.finalize(st))
                if not keys and not pipe.by:
                    # zero rows still yields one all-groups row
                    for fn in pipe.funcs:
                        cols[fn.out_name].append(fn.finalize(fn.new_state()))
                self.next_p.write_block(BlockResult.from_columns(cols)
                                        if any(cols.values())
                                        else BlockResult(0))
                self.next_p.flush()
        return P(next_p)


# ---------------- parsing ----------------

def parse_pipes(lex: Lexer) -> list:
    pipes = []
    while True:
        pipes.append(parse_pipe(lex))
        if lex.is_keyword("|"):
            lex.next_token()
            continue
        break
    return pipes


def parse_pipe(lex: Lexer):
    name = lex.token.lower()
    fn = _PIPE_PARSERS.get(name)
    if fn is None:
        raise ParseError(f"unknown pipe {lex.token!r}")
    lex.next_token()
    return fn(lex)


def _parse_field_name(lex: Lexer) -> str:
    from .parser import _get_compound_token
    tok = _get_compound_token(lex, stop=(",", "(", ")", "[", "]", "|", "*",
                                         ""))
    return tok


def _parse_field_list(lex: Lexer) -> list:
    fields = []
    while True:
        name = _parse_field_name(lex)
        if lex.is_keyword("*") and not lex.is_skipped_space:
            name += "*"          # wildcard selection: `fields req_*`
            lex.next_token()
        fields.append(name)
        if lex.is_keyword(","):
            lex.next_token()
            continue
        break
    return fields


def _parse_fields(lex: Lexer):
    return PipeFields(_parse_field_list(lex))


def _parse_delete(lex: Lexer):
    return PipeDelete(_parse_field_list(lex))


def _parse_as_pairs(lex: Lexer) -> list:
    pairs = []
    while True:
        src = _parse_field_name(lex)
        if lex.is_keyword("as"):
            lex.next_token()
        dst = _parse_field_name(lex)
        pairs.append((src, dst))
        if lex.is_keyword(","):
            lex.next_token()
            continue
        break
    return pairs


def _parse_copy(lex: Lexer):
    return PipeCopy(_parse_as_pairs(lex))


def _parse_rename(lex: Lexer):
    return PipeRename(_parse_as_pairs(lex))


def _parse_uint(lex: Lexer, what: str) -> int:
    v = parse_number(lex.token)
    if math.isnan(v) or v < 0 or v != int(v):
        raise ParseError(f"invalid {what} {lex.token!r}")
    lex.next_token()
    return int(v)


def _parse_limit(lex: Lexer):
    return PipeLimit(_parse_uint(lex, "limit"))


def _parse_offset(lex: Lexer):
    return PipeOffset(_parse_uint(lex, "offset"))


def _parse_where(lex: Lexer):
    from .parser import parse_filter_or
    return PipeWhere(parse_filter_or(lex, ""))


def _parse_by_fields(lex: Lexer) -> list:
    """Parse `by (f1, f2:bucket, ...)` — 'by' already consumed or implied."""
    out = []
    if not lex.is_keyword("("):
        raise ParseError("missing '(' after by")
    lex.next_token()
    while not lex.is_keyword(")"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        name = lex.token
        lex.next_token()
        bf = ByField(name)
        if lex.is_keyword(":"):
            lex.next_token()
            bf.bucket = lex.token
            lex.next_token()
            if lex.is_keyword("offset"):
                lex.next_token()
                bf.bucket_offset = lex.token
                lex.next_token()
        out.append(bf)
    lex.next_token()
    return out


def _parse_sort(lex: Lexer):
    by = []
    if lex.is_keyword("by"):
        lex.next_token()
        if not lex.is_keyword("("):
            raise ParseError("missing '(' after sort by")
        lex.next_token()
        while not lex.is_keyword(")"):
            if lex.is_keyword(","):
                lex.next_token()
                continue
            f = _parse_field_name(lex)
            desc = False
            if lex.is_keyword("desc"):
                desc = True
                lex.next_token()
            elif lex.is_keyword("asc"):
                lex.next_token()
            by.append((f, desc))
        lex.next_token()
    p = PipeSort(by)
    while True:
        if lex.is_keyword("desc"):
            p.desc = True
            lex.next_token()
        elif lex.is_keyword("asc"):
            lex.next_token()
        elif lex.is_keyword("limit"):
            lex.next_token()
            p.limit = _parse_uint(lex, "limit")
        elif lex.is_keyword("offset"):
            lex.next_token()
            p.offset = _parse_uint(lex, "offset")
        elif lex.is_keyword("rank"):
            lex.next_token()
            if lex.is_keyword("as"):
                lex.next_token()
            p.rank_field = _parse_field_name(lex)
        elif lex.is_keyword("partition"):
            lex.next_token()
            if lex.is_keyword("by"):
                lex.next_token()
            if not lex.is_keyword("("):
                raise ParseError("missing '(' after partition by")
            lex.next_token()
            while not lex.is_keyword(")"):
                if lex.is_keyword(","):
                    lex.next_token()
                    continue
                p.partition_by.append(_parse_field_name(lex))
            lex.next_token()
        else:
            break
    return p


def _parse_uniq(lex: Lexer):
    by = []
    if lex.is_keyword("by"):
        lex.next_token()
        bfs = _parse_by_fields(lex)
        by = [b.name for b in bfs]
    p = PipeUniq(by)
    while True:
        if lex.is_keyword("with"):
            lex.next_token()
            if lex.is_keyword("hits"):
                p.with_hits = True
                lex.next_token()
        elif lex.is_keyword("limit"):
            lex.next_token()
            p.limit = _parse_uint(lex, "limit")
        else:
            break
    return p


def _parse_first_last(lex: Lexer, desc: bool):
    # `first N by (field)` == sort by (field) limit N
    n = 1
    if not lex.is_keyword("by") and not lex.is_end() and \
            not lex.is_keyword("|"):
        n = _parse_uint(lex, "first/last count")
    by = []
    if lex.is_keyword("by"):
        lex.next_token()
        if not lex.is_keyword("("):
            raise ParseError("missing '(' after by")
        lex.next_token()
        while not lex.is_keyword(")"):
            if lex.is_keyword(","):
                lex.next_token()
                continue
            f = _parse_field_name(lex)
            d = False
            if lex.is_keyword("desc"):
                d = True
                lex.next_token()
            by.append((f, d))
        lex.next_token()
    return PipeSort(by or [("_time", False)], desc=desc, limit=n)


def parse_stats_func(lex: Lexer):
    name = lex.token.lower()
    ctor = _STATS_FUNCS.get(name)
    if ctor is None:
        raise ParseError(f"unknown stats function {lex.token!r}")
    lex.next_token()
    if not lex.is_keyword("("):
        raise ParseError(f"missing '(' after {name}")
    lex.next_token()
    args = []
    while not lex.is_keyword(")"):
        if lex.is_keyword(","):
            lex.next_token()
            continue
        if lex.is_keyword("*"):
            lex.next_token()
            continue
        args.append(_parse_field_name(lex))
    lex.next_token()
    fn = ctor(args)
    # optional limit N (count_uniq/uniq_values/values)
    if lex.is_keyword("limit") and hasattr(fn, "limit"):
        lex.next_token()
        fn.limit = _parse_uint(lex, "limit")
    # optional per-func row guard: `count() if (error)` (reference
    # pipe_stats.go statsFuncs iff)
    if lex.is_keyword("if"):
        from .pipes_transform import parse_if_filter
        fn.iff = parse_if_filter(lex)
    if lex.is_keyword("as"):
        lex.next_token()
        fn.out_name = _parse_field_name(lex)
    elif not lex.is_end() and not lex.is_keyword(",", "|", ")") \
            and not lex.is_keyword("by"):
        fn.out_name = _parse_field_name(lex)
    return fn


def _quantile_ctor(args):
    if len(args) < 2:
        raise ParseError("quantile(phi, field) expects 2+ args")
    phi = parse_number(args[0])
    if math.isnan(phi) or not 0 <= phi <= 1:
        raise ParseError(f"invalid quantile phi {args[0]!r}")
    return sf.StatsQuantile(phi, args[1:])


_STATS_FUNCS = {
    "count": sf.StatsCount,
    "count_empty": sf.StatsCountEmpty,
    "count_uniq": sf.StatsCountUniq,
    "count_uniq_hash": sf.StatsCountUniqHash,
    "sum": sf.StatsSum,
    "sum_len": sf.StatsSumLen,
    "min": sf.StatsMin,
    "max": sf.StatsMax,
    "avg": sf.StatsAvg,
    "uniq_values": sf.StatsUniqValues,
    "values": sf.StatsValues,
    "median": sf.StatsMedian,
    "quantile": _quantile_ctor,
    "row_any": sf.StatsRowAny,
    "histogram": sf.StatsHistogram,
    "rate": sf.StatsRate,
    "rate_sum": sf.StatsRateSum,
    "row_min": sf.StatsRowMin,
    "row_max": sf.StatsRowMax,
    "json_values": sf.StatsJSONValues,
}


def _parse_stats(lex: Lexer):
    by = []
    if lex.is_keyword("by"):
        lex.next_token()
        by = _parse_by_fields(lex)
    funcs = []
    while True:
        funcs.append(parse_stats_func(lex))
        if lex.is_keyword(","):
            lex.next_token()
            continue
        break
    # alt form: `stats count() by (f)`
    if lex.is_keyword("by") and not by:
        lex.next_token()
        by = _parse_by_fields(lex)
    if not funcs:
        raise ParseError("stats needs at least one function")
    return PipeStats(by, funcs)


def _parse_count_shorthand(lex: Lexer):
    """Top-level `| count()` == `| stats count()`."""
    if lex.is_keyword("("):
        lex.next_token()
        if not lex.is_keyword(")"):
            raise ParseError("count() takes no args")
        lex.next_token()
    fn = sf.StatsCount([])
    if lex.is_keyword("as"):
        lex.next_token()
        fn.out_name = _parse_field_name(lex)
    return PipeStats([], [fn])


_PIPE_PARSERS = {
    "fields": _parse_fields,
    "keep": _parse_fields,
    "delete": _parse_delete,
    "del": _parse_delete,
    "rm": _parse_delete,
    "drop": _parse_delete,
    "copy": _parse_copy,
    "cp": _parse_copy,
    "rename": _parse_rename,
    "mv": _parse_rename,
    "limit": _parse_limit,
    "head": _parse_limit,
    "offset": _parse_offset,
    "skip": _parse_offset,
    "where": _parse_where,
    "filter": _parse_where,
    "sort": _parse_sort,
    "order": _parse_sort,
    "uniq": _parse_uniq,
    "stats": _parse_stats,
    "count": _parse_count_shorthand,
    "first": lambda lex: _parse_first_last(lex, desc=False),
    "last": lambda lex: _parse_first_last(lex, desc=True),
}


def register_pipe(name: str, parse_fn) -> None:
    _PIPE_PARSERS[name] = parse_fn


def compute_needed_fields(pipes: list) -> set:
    """Back-to-front needed-columns set for the storage scan: which columns
    the pipe chain can ever read from a raw block.  {"*"} means all
    (reference getNeededColumns -> prefixfilter — storage_search.go:123)."""
    needed = {"*"}
    for p in reversed(pipes):
        needed = p.input_fields(needed)
        if "*" in needed:
            needed = {"*"} | needed
    return needed


# transform pipes (extract/format/math/unpack/replace/top/...) and aux
# pipes (join/union/stream_context/...) register themselves on import;
# must come after the registry exists
from . import pipes_transform  # noqa: E402,F401  (registration side effect)
from . import pipes_aux        # noqa: E402,F401  (registration side effect)
