# victorialogs_tpu build/test entry points.
#
# The native host core (victorialogs_tpu/native/libvlnative.so) also builds
# itself on first import; this target is for explicit/offline builds.

NATIVE_DIR := victorialogs_tpu/native

.PHONY: all native test race lint check help bench bench-bloom \
	bench-pipeline bench-cluster-obs bench-concurrent bench-emit \
	bench-explain bench-faults bench-ingest bench-journal \
	bench-standing bench-wire clean

all: native

help:
	@echo "victorialogs_tpu targets:"
	@echo "  make check    pre-push gate: lint + tier-1 suite + race smoke"
	@echo "  make lint     vlint static analysis + env-table drift + compile sweep"
	@echo "  make test     full test suite (fail-fast)"
	@echo "  make race     concurrency suites under both runtime sanitizers"
	@echo "  make native   build the native host core explicitly"
	@echo "  make bench-*  recorded performance rounds (see PERF.md)"

native: $(NATIVE_DIR)/libvlnative.so

$(NATIVE_DIR)/libvlnative.so: $(NATIVE_DIR)/vlnative.cpp
	g++ -O3 -std=c++17 -shared -fPIC -o $@ $<

test:
	python -m pytest tests/ -x -q

# the concurrency suites under BOTH runtime sanitizers: the lock-order
# shim (VLINT_LOCK_ORDER=1, cross-validated against the static graph at
# session end) and the vlsan end-of-test invariant sweep (on by
# default; VLSAN=0 kills it).  This is the ROADMAP standing gate's
# "run periodically" instruction as one command.
race:
	VLINT_LOCK_ORDER=1 python -m pytest tests/test_storage_races.py \
		tests/test_ingest_mt.py tests/test_concurrent_ingest.py \
		tests/test_sched.py tests/test_chaos.py -q

# repo-native static analysis (tools/vlint/README.md) + the README
# env-table drift gate (generated from victorialogs_tpu/config.py) +
# a compile sweep.  Fails on any finding not in
# tools/vlint/baseline.json (which stays EMPTY: fix or annotate).
lint:
	python -m tools.vlint victorialogs_tpu/
	python -m tools.vlint --check-env-table
	python -m compileall -q victorialogs_tpu tools tests

# the single pre-push gate: static analysis (including the v3
# interprocedural graph passes), the tier-1 suite on the CPU backend,
# and a race-suite smoke under both runtime sanitizers.  Green here ==
# safe to push; `make race` remains the full concurrency soak.
check: lint
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'
	VLINT_LOCK_ORDER=1 python -m pytest tests/test_storage_races.py -q

bench:
	python bench.py

# prune throughput: per-block bloom loop vs batched plane probe at 10k
# blocks (filter-index subsystem; fails under 5x — see PERF.md)
bench-bloom:
	python tools/bench_bloom.py

# many-small-parts async pipeline: serial vs windowed vs packed on the
# jax-CPU backend (fails under 4x dispatch cut / 1.5x wall — PERF.md)
bench-pipeline:
	python tools/bench_pipeline.py --json BENCH_pipeline.json

# same bench + the concurrent-clients mode (8 threaded clients, p50/p99
# + aggregate rows/s, vl_active_queries sampled mid-run), the tenant-mix
# fairness round (2 heavy + 4 light clients, unmanaged VL_SCHED=0 vs
# managed: light p99 must not regress, aggregate within bounds) and the
# HTTP shed probe (capped tenant sheds 429 + Retry-After + counters) —
# PERF.md round 8
bench-concurrent:
	python tools/bench_pipeline.py --clients 8 --json BENCH_pipeline.json

# emit phase: per-row dicts + json.dumps vs the columnar native NDJSON
# path on the 32x2048 bench shape (fails under 2x — PERF.md)
bench-emit:
	python tools/bench_emit.py --json BENCH_emit.json

# self-telemetry journal overhead: bench-pipeline rows workload with
# the journal off (structurally zero, asserted) vs on (one query_done
# event per query, ingested into the same storage); fails past the
# PR 4 trace-overhead bound (10% + 2 ms) — PERF.md
bench-journal:
	python tools/bench_journal.py --json BENCH_journal.json

# query EXPLAIN + cost-model accountability: the continuous plan-time
# pricing pass must stay within the PR 4 trace-overhead bound
# (10% + 2 ms), explain=1 must be O(headers) (>=20x faster than
# execution, zero device dispatches), and the median cost-model
# relative error (duration/bytes) must stay under the recorded bounds
# — PERF.md round 11
bench-explain:
	python tools/bench_explain.py --json BENCH_explain.json

# cluster wire protocol: typed columnar frames vs legacy JSON frames on
# a real 2-node scatter-gather; asserts bit-identical hit sets, >=2x
# frontend rows/s, and zero typed frames under VL_WIRE_TYPED=0 —
# PERF.md round 10
bench-wire:
	python tools/bench_wire.py --json BENCH_wire.json

# network-chaos round on a real 3-node cluster + fault proxy: strict
# failure bounded by the deadline (refuse AND hang), partial-results
# exactness, breaker recovery latency, and the ingest-outage
# spool-replay zero-loss assertion — recorded into BENCH_faults.json
# (PERF.md chaos round)
bench-faults:
	python tools/bench_faults.py --json BENCH_faults.json

# cluster observability plane on a real 3-node cluster: rollup overhead
# (<=1.10x concurrent p50) + the rollup-vs-node-sum differential,
# federated active_queries completeness with parent_qid linkage, and
# cancel-propagation kill latency vs the disconnect-probe path —
# recorded into BENCH_cluster_obs.json (PERF.md round)
bench-cluster-obs:
	python tools/bench_cluster_obs.py --json BENCH_cluster_obs.json

# standing queries + per-part result cache: repeated-query round (2nd
# run must submit >=5x fewer dispatches, hit ratio >= 0.9, cached
# parts priced ~0 in EXPLAIN, post-flush run re-dispatches only the
# head part) and the 100-subscriber standing-panel round (ONE
# evaluation per refresh, every subscriber's delta == a fresh full
# evaluation) — PERF.md round
bench-standing:
	python tools/bench_standing.py --json BENCH_standing.json

# typed ingest wire format i1 end-to-end: library hot path (+4-core
# Amdahl projection), i1 codec encode/decode rates, typed-vs-legacy
# insert hop (>=3x, zero per-row json.loads pinned by counters),
# spool-replay chaos (zero rows lost, zero re-encodes), and the
# typed-vs-legacy stored-data differential — PERF.md round 16 — plus
# the sharded block-build round: columnar arena encode vs the list
# path (>=1.5x) and serial-vs-sharded insert hop against the 352k
# baseline (>=2x asserted only when >=2 cores) — PERF.md round 18
bench-ingest:
	python tools/bench_ingest.py --json BENCH_ingest.json

clean:
	rm -f $(NATIVE_DIR)/libvlnative.so
