# victorialogs_tpu build/test entry points.
#
# The native host core (victorialogs_tpu/native/libvlnative.so) also builds
# itself on first import; this target is for explicit/offline builds.

NATIVE_DIR := victorialogs_tpu/native

.PHONY: all native test bench clean

all: native

native: $(NATIVE_DIR)/libvlnative.so

$(NATIVE_DIR)/libvlnative.so: $(NATIVE_DIR)/vlnative.cpp
	g++ -O3 -std=c++17 -shared -fPIC -o $@ $<

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

clean:
	rm -f $(NATIVE_DIR)/libvlnative.so
