"""Pallas-vs-XLA scan kernel micro-benchmark (invoked by bench.py in a
subprocess so an unproven hardware lowering can never take down the main
benchmark run).  Prints one JSON line."""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> int:
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_097_152
    n_rows = max(512, (n_rows // 512) * 512)   # pallas tile alignment
    width = 128
    import jax
    import jax.numpy as jnp

    from victorialogs_tpu.tpu import kernels as K
    from victorialogs_tpu.tpu.kernels_pallas import (PALLAS_AVAILABLE,
                                                     match_scan_pallas,
                                                     pallas_ok)
    if not PALLAS_AVAILABLE:
        print(json.dumps({"pallas": "import unavailable"}))
        return 0

    rng = np.random.default_rng(7)
    mat = np.full((n_rows, width), 0xFF, dtype=np.uint8)
    base = np.frombuffer(
        (b"GET /api/items status=200 deadline exceeded retry ok " * 3),
        dtype=np.uint8)
    lens = rng.integers(20, width - 1, n_rows).astype(np.int32)
    take = min(base.shape[0], width - 1)
    mat[:, :take] = base[:take]
    assert pallas_ok(n_rows, width)

    rows_d = jax.device_put(jnp.asarray(mat))
    lens_d = jax.device_put(jnp.asarray(lens))
    pat = jnp.asarray(np.frombuffer(b"deadline", dtype=np.uint8))
    # CPU backends only run pallas in interpret mode (slow but validates
    # the plumbing); real hardware uses the Mosaic lowering
    interp = jax.default_backend() not in ("tpu",)

    # force sync completion mode before timing (axon: timings are fake
    # until the first device->host download)
    float(jnp.sum(jnp.ones(8)))

    def timed(fn, reps=5):
        out = fn()          # warmup/compile
        np.asarray(out)
        t0 = time.time()
        for _ in range(reps):
            np.asarray(fn())
        return (time.time() - t0) / reps

    xla_s = timed(lambda: K.match_scan(rows_d, lens_d, pat, 8,
                                       K.MODE_PHRASE, True, True))
    pl_s = timed(lambda: match_scan_pallas(rows_d, lens_d, pat, 8,
                                           K.MODE_PHRASE, True, True,
                                           interpret=interp))
    same = bool(np.array_equal(
        np.asarray(K.match_scan(rows_d, lens_d, pat, 8, K.MODE_PHRASE,
                                True, True)),
        np.asarray(match_scan_pallas(rows_d, lens_d, pat, 8,
                                     K.MODE_PHRASE, True, True,
                                     interpret=interp))))
    print(json.dumps({
        "backend": jax.default_backend(),
        "interpret_mode": interp,
        "n_rows": n_rows,
        "xla_rows_per_sec": round(n_rows / xla_s),
        "pallas_rows_per_sec": round(n_rows / pl_s),
        "pallas_speedup_vs_xla": round(xla_s / pl_s, 2),
        "identical": same,
    }))
    return 0 if same else 1


if __name__ == "__main__":
    sys.exit(main())
